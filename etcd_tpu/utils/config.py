"""Run-level Raft knobs — parity with the reference's ``raft.Config``
(raft/raft.go:116-199), minus the Go-runtime-specific fields (Storage/Logger)
and with byte limits re-expressed as entry counts (payloads are fixed-width
words on device).

These are *static* (trace-time) parameters: they select code paths and
bounds inside the jitted step, so changing them recompiles.
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class RaftConfig:
    # tick counts (raft.Config.ElectionTick/HeartbeatTick)
    election_tick: int = 10
    heartbeat_tick: int = 1
    # flow control: raft.Config.MaxInflightMsgs; must be <= Spec.W
    max_inflight: int = 4
    # raft.Config.MaxUncommittedEntriesSize, in entries (0 disables like ref)
    max_uncommitted: int = 0
    # raft.Config.PreVote (thesis §9.6)
    pre_vote: bool = False
    # raft.Config.CheckQuorum (leader steps down without quorum contact)
    check_quorum: bool = False
    # raft.Config.ReadOnlyOption: False=ReadOnlySafe, True=ReadOnlyLeaseBased
    read_only_lease_based: bool = False
    # raft.Config.DisableProposalForwarding
    disable_proposal_forwarding: bool = False
    # Which synthesized LOCAL message steps node_round traces, in its
    # fixed order [hup, inbox..., prop, read_index]. Each listed step is
    # one more full masked pass over fleet state per round — the round
    # program's unit of cost — and a step whose inputs are all-absent at
    # runtime is a pure no-op that still pays that pass. Steady-state
    # perf programs (bench: elected fleets, one proposal per group per
    # round, no reads) drop "hup"/"read_index" AT TRACE TIME and keep a
    # second full-step program for the election/read phases; equivalence
    # of the dropped-step program on absent inputs is proven by
    # tests/test_local_steps.py. NOTE: timeout-driven campaigns ALSO ride
    # the hup step (tick_timers' fire flag) — dropping "hup" is only
    # sound for programs that never tick (the bench steady loop) or
    # whose elections are driven externally. "tick" gates the
    # tick_timers pass the same way: with do_tick all-False it is a pure
    # masked no-op, so programs that never tick drop it at trace time.
    local_steps: tuple = ("tick", "hup", "prop", "read_index")
    # Which MESSAGE TYPES this program's step handles (None = all). Each
    # handler block in process_message/_step_* is one or more full masked
    # passes over fleet state that XLA must execute even when its type
    # mask is runtime-false — at 5 serial message slots per round, the
    # ~14 steady-dead handler classes are most of the round's HBM
    # traffic. A steady-state program declares its traffic, e.g.
    # (MSG_APP, MSG_APP_RESP, MSG_PROP), and the other handlers are
    # DROPPED AT TRACE TIME. Contract: bit-identical to the full program
    # as long as no message of an omitted type reaches the step
    # (tests/test_local_steps.py proves it on live steady traffic); a
    # program that might see elections, snapshots, leadership transfer
    # or reads must keep the default. Term/lease preamble and candidate
    # demotion stay unconditionally — they key on message TERMS and
    # roles, not on declared classes.
    message_classes: tuple | None = None
    # Which ENTRY types this program's APPLY path handles (None = all).
    # The A-slot apply scan (apply_round) replays apply_conf_change's
    # joint-config mask algebra on every one of Spec.A serial slots even
    # when no conf-change entry can be committed — profiled at 9.5% of
    # the steady round (PROFILE.md round 5), the largest single source
    # line after deferred emission landed. A program that never proposes
    # membership changes declares entry_classes=("normal",) and the
    # conf-change apply block, the auto-leave pass and the leave-entry
    # append DROP OUT AT TRACE TIME. Contract: bit-identical while no
    # ENTRY_CONF_CHANGE entry commits and the fleet neither starts in
    # nor enters a joint configuration
    # (tests/test_apply_specialization.py proves it on steady traffic).
    entry_classes: tuple | None = None
    # Compact each node's inbox (nonempty slots to the front, original
    # order preserved) and process only the first `inbox_bound` slots per
    # round instead of all M*K. Messages past the bound are DROPPED —
    # legal by the transport contract ("Send MUST NOT block / drop is OK",
    # etcdserver/raft.go:107-110; rafttest/network.go:106-108) and
    # recovered by Raft's own retransmission/re-election machinery. The
    # round program's dominant cost is the serial per-slot message loop
    # (profiled: each slot replays the full masked step), so bounding the
    # live slots is a direct round-time multiplier. In the replication
    # steady state a node receives at most max(M-1, K) messages per round
    # (the leader's M-1 acks), so inbox_bound=M-1 is lossless there.
    # 0 disables (test/golden paths: exact slot semantics).
    inbox_bound: int = 0
    # Coalesce the leader's commit-index propagation: suppress the empty
    # commit-refresh MsgApp fired while processing each MsgAppResp
    # (raft.go:1259-1263 bcastAppend-on-commit) and instead flush ONE
    # (possibly empty) append at end of round to every follower that got
    # no message this round. In the lockstep engine an ack-driven refresh
    # and a same-round proposal append carry the same commit index, so
    # the refresh is redundant whenever the round also proposes — with
    # coalescing the steady state is exactly one append + one ack per
    # follower per round (half the message load, and inbox_bound=M-1
    # becomes lossless). Suppressing a send is legal by the transport
    # drop contract; the end-of-round flush preserves commit liveness.
    # Off for the golden/test paths (exact reference message schedule).
    coalesce_commit_refresh: bool = False
    # Process the fleet's clusters axis in this many sequential chunks per
    # round (clusters are independent, so per-cluster math is unchanged).
    # The round program's HLO temps scale with the resident C, so chunking
    # bounds peak HBM while the full fleet state stays device-resident —
    # how one chip holds the 1M-group configuration (SCALE_RESULTS.jsonl).
    # Single-device only: slicing a sharded trailing axis would force
    # cross-device traffic (the 8-chip mesh holds 131k/chip and needs no
    # chunking). 1 disables.
    fleet_chunks: int = 1
    # The emission restructure (PROFILE.md): handlers inside the serial
    # message scan record per-destination reply/send intents in small
    # [M]-vectors (ops/outbox.py PendingWire) instead of writing [K, M]
    # message planes, and node_round materializes them with ONE
    # post-scan AppResp emit + ONE merged maybe_send_append + ONE
    # proposal-forward emit. With the steady message_classes this leaves
    # ZERO outbox writes inside the scan, so the scan carry shrinks to
    # NodeState + a dozen [M]-vectors. Semantics: last-writer-wins per
    # destination — coalescing is legal by the transport drop contract,
    # and BIT-IDENTICAL in the steady state where each peer receives at
    # most one reply-worthy message per round
    # (tests/test_deferred_emit.py). PRECONDITIONS (like local_steps):
    # requires coalesce_commit_refresh; assumes no in-flight leadership
    # transfer (the MsgTimeoutNow emit is compiled out — sound because
    # MSG_TRANSFER_LEADER is not in any steady message_classes, so no
    # transfer can start). Off for golden/test paths.
    deferred_emit: bool = False
    # The fleet memory diet, part 1 (PROFILE.md round 6): carry the fleet
    # state BETWEEN rounds in the bit/width-packed storage form
    # (models/state.py PackedFleet) instead of the full NodeState pytree.
    # The round program unpacks at entry and repacks at exit — with
    # fleet_chunks > 1 the pack/unpack happens INSIDE the chunk loop, so
    # the unpacked temps are chunk-local and the resident fleet is the
    # ~2.4x-smaller packed form. SCALE MODE ONLY, two contracts (both the
    # wire_int16 class of range contracts): (a) every index/term-valued
    # field must stay below 32768 (bench/chaos horizons, not long-lived
    # servers); (b) 2 * election_tick must fit the packed timer lanes
    # (state.py PACK_TIMER_BITS; validated at build time). Timer lanes
    # SATURATE at their cap — exact for promotable nodes (elapsed resets
    # at the timeout), and semantically equivalent for non-promotable
    # nodes whose elapsed grows without firing (any value >= the
    # randomized timeout behaves identically). Bit-identical trajectories
    # vs the unpacked program are proven by tests/test_packed_state.py.
    packed_state: bool = False
    # The fleet memory diet, part 2: complete PROFILE.md's emission
    # restructure by removing the dense outbox from the message-scan
    # carry ENTIRELY. Requires deferred_emit and a message_classes
    # declaration under which every in-scan handler records PendingWire
    # intents instead of emitting ({MSG_APP, MSG_APP_RESP, MSG_PROP} —
    # exactly the steady wire traffic): the scan then carries only
    # (NodeState, PendingWire) and the K-slot outbox is packed ONCE by
    # the post-scan merge, so XLA never round-trips the [K, M] message
    # planes through the serial slot loop's carry. Bit-identical to the
    # deferred program by construction (the dropped carry leaves are
    # provably never written inside the scan; tests/test_sparse_outbox.py
    # proves it against the immediate-emission program end to end).
    sparse_outbox: bool = False
    # The fleet memory diet, part 3: store the carried inter-round
    # message tensor in the inbox-compacted form — [bound, M(to), C]
    # slots instead of the dense [M(from), K*M(to), C] plane. Requires
    # inbox_bound > 0. The per-receiver compaction node_round already
    # performs at scan entry moves to the round BOUNDARY (after the
    # keep-mask, before storage), so the resident wire shrinks K*M/bound
    # x (10 -> 4 slots at the bench geometry) and the next round scans
    # the stored slots directly. Bit-identical to the dense carry by
    # construction: same messages, same order, same drop set — proven
    # over full-program scenarios (elections, drops, snapshots) by
    # tests/test_sparse_outbox.py. NOT for the chaos tiers: the held-
    # buffer delay machinery and crash traffic wipes address the dense
    # [from, K, to] plane (harness/chaos.py validates).
    compact_wire: bool = False
    # Store the carried inter-round message tensor (the "wire") as int16
    # instead of int32: halves the resident inbox, which at the 1M-group
    # configuration is the largest single fleet buffer. Casts happen at
    # the round boundary; all round math stays int32. SCALE MODE ONLY:
    # every wire-carried value (terms, log indexes, commit indexes,
    # payload words, read contexts) must stay below 32768 — true for
    # bench/chaos horizons (hundreds of rounds, small payload alphabet),
    # NOT for long-lived servers whose payload words grow unboundedly.
    wire_int16: bool = False

    def __post_init__(self):
        if self.heartbeat_tick <= 0:
            raise ValueError("heartbeat tick must be greater than 0")
        if self.election_tick <= self.heartbeat_tick:
            raise ValueError("election tick must be greater than heartbeat tick")
        if self.read_only_lease_based and not self.check_quorum:
            raise ValueError("CheckQuorum must be enabled for lease-based reads")
        known = {"tick", "hup", "prop", "read_index"}
        bad = set(self.local_steps) - known
        if bad:
            raise ValueError(f"unknown local_steps {sorted(bad)}; known: "
                             f"{sorted(known)}")
        if "tick" in self.local_steps and "hup" not in self.local_steps:
            # tick_timers' election-timeout fire rides the hup step; a
            # ticking program without it silently discards every campaign
            raise ValueError('local_steps with "tick" requires "hup" '
                             "(timeout campaigns ride the hup step)")
        if self.message_classes is not None:
            # a kept local injection step whose message class is compiled
            # out would synthesize messages nobody handles
            from etcd_tpu import types as _t

            need = {"hup": _t.MSG_HUP, "prop": _t.MSG_PROP,
                    "read_index": _t.MSG_READ_INDEX}
            for step, mtype in need.items():
                if step in self.local_steps and mtype not in self.message_classes:
                    raise ValueError(
                        f'local step "{step}" is kept but its message type '
                        "is not in message_classes — its messages would be "
                        "silently swallowed"
                    )
        if self.entry_classes is not None:
            bad = set(self.entry_classes) - {"normal", "conf_change"}
            if bad:
                # a typo'd class name must not silently drop the
                # conf-change apply block
                raise ValueError(
                    f"unknown entry_classes {sorted(bad)}; known: "
                    "['conf_change', 'normal']")
        if self.sparse_outbox:
            from etcd_tpu import types as _t

            if not self.deferred_emit:
                raise ValueError("sparse_outbox requires deferred_emit "
                                 "(the scan-body handlers must record "
                                 "PendingWire intents, not emit)")
            steady = {_t.MSG_APP, _t.MSG_APP_RESP, _t.MSG_PROP}
            if self.message_classes is None or \
                    not set(self.message_classes) <= steady:
                # soundness is BY CONSTRUCTION: under these classes every
                # reachable in-scan handler is a PendingWire recorder, so
                # dropping the outbox planes from the scan carry cannot
                # lose a write. Any wider class set has in-scan emit
                # sites (votes, heartbeats, snapshots, forwards) whose
                # writes would be silently discarded.
                raise ValueError(
                    "sparse_outbox requires message_classes ⊆ "
                    "{MSG_APP, MSG_APP_RESP, MSG_PROP} — other handler "
                    "classes emit inside the scan")
        if self.compact_wire and self.inbox_bound <= 0:
            raise ValueError("compact_wire stores the inbox in its "
                             "compacted form and needs inbox_bound > 0")
        if self.deferred_emit and not self.coalesce_commit_refresh:
            # without coalescing, the leader's per-ack commit broadcast
            # fires inside the scan — exactly the write the deferral is
            # supposed to remove, and its send set depends on mid-scan
            # commit state that the post-scan flush cannot reconstruct
            raise ValueError("deferred_emit requires "
                             "coalesce_commit_refresh")

    @property
    def max_uncommitted_entries(self) -> int:
        return self.max_uncommitted if self.max_uncommitted > 0 else (1 << 30)


@dataclasses.dataclass(frozen=True)
class CrashConfig:
    """Crash–restart fault model for the chaos tier (harness/chaos.py).

    Like the per-round crash probability, these knobs ride as RUNTIME
    operands of the epoch program (run_chaos passes down_rounds as an
    i32 and durability as a keep_log bool), alongside the
    drop/delay/partition probabilities — one traced program serves every
    crash mix; only crash_p > 0 vs == 0 changes program structure.

    The durability model mirrors the reference's fsync discipline
    (raft/node.go:586-593 MustSync + the Ready contract "persist before
    send"): HardState term/vote survive a crash outright, the log survives
    up to a per-node ``stable`` index that lags ``last_index`` by one
    lockstep round (the modeled fsync latency), commit is capped at the
    durable log (commit-only advances never force an fsync), and
    snapshots/compaction are synchronously durable. Entries past
    ``stable`` are LOST — which is safe exactly because the engine wipes
    the crashed node's in-flight outgoing messages with it, so no
    acknowledgement of an unsynced entry is ever observed (the lockstep
    analog of "the ack is only sent after fsync").
    """

    # rounds a crashed node stays down before restarting with a fresh
    # randomized election timeout (the tester's SIGKILL->restart window)
    down_rounds: int = 3
    # "stable": the fsync-lag model above (the honest one).
    # "none": a deliberately-broken model that persists nothing past the
    # last snapshot — it exists so tests can prove the leader-completeness
    # checker actually fires when committed entries disappear.
    durability: str = "stable"

    def __post_init__(self):
        if self.down_rounds < 1:
            # a 0-round crash would restart within the crash round itself,
            # before its wiped in-flight messages are even dropped
            raise ValueError("down_rounds must be >= 1")
        if self.durability not in ("stable", "none"):
            raise ValueError(
                f"unknown durability {self.durability!r}; "
                "known: ['none', 'stable']")


# fault-mix names the membership tier's palette builder understands
# (harness/chaos.py member_palette); chaos_run.py validates CHAOS_MEMBER_MIX
# against this tuple before any device work.
MEMBER_MIXES = ("standard", "simple", "shrink")


@dataclasses.dataclass(frozen=True)
class MemberChaosConfig:
    """Membership-change fault model for the chaos tier (harness/chaos.py).

    Like the crash knobs, everything here that shapes behavior at runtime
    rides as RUNTIME operands of the epoch program: the conf-change word
    palette is an i32[P] operand sampled per (round, group), and the two
    crash-boost factors are f32 operands of the targeted crash scheduler —
    one traced program serves every membership mix and every targeting
    intensity; only member_p > 0 vs == 0 changes program structure.

    ``initial_voters`` boots each group with members 0..initial_voters-1
    as voters and the rest outside the config, so add-voter/add-learner
    words have free slots to grow into (0 = all M members start as
    voters, the legacy crash-tier shape). The palette never removes or
    demotes members 0 and 1: the fsync-lag crash model needs >= 2 voters
    (run_chaos's M >= 2 guard), and an unconstrained remove schedule
    could legally drain the voter set to a singleton — or to empty, which
    the host-side Changer forbids but the device path applies
    unconditionally.

    The crash boosts concentrate the SAME expected crash budget
    (crash_p * lanes) on fault windows instead of spreading it Bernoulli-
    uniformly: ``snap_crash_boost`` multiplies the per-lane crash
    probability inside the snapshot-install window (MsgSnap in flight to
    the node, or a leader with a peer in PR_SNAPSHOT between send and
    ack), ``member_crash_boost`` inside the membership-sensitive window
    (joint config, or a committed-but-unapplied conf change). 1.0 = no
    targeting (pure Bernoulli, the PR-1 behavior).
    """

    mix: str = "standard"          # palette name, one of MEMBER_MIXES
    initial_voters: int = 0        # 0 = all M members boot as voters
    snap_crash_boost: float = 1.0
    member_crash_boost: float = 1.0

    def __post_init__(self):
        if self.mix not in MEMBER_MIXES:
            raise ValueError(
                f"unknown member mix {self.mix!r}; known: "
                f"{sorted(MEMBER_MIXES)}")
        if self.initial_voters == 1 or self.initial_voters < 0:
            # a singleton commits its own append before the modeled fsync
            # completes — the shape the crash tier already rejects
            raise ValueError("initial_voters must be 0 (= all) or >= 2")
        if self.snap_crash_boost < 1.0 or self.member_crash_boost < 1.0:
            raise ValueError("crash boosts must be >= 1.0 (1.0 = uniform)")
