"""Auth store — users, RBAC roles with key-interval permissions, tokens.

Mirrors ``server/auth/store.go``: bcrypt'd users (scrypt here — stdlib;
bcrypt is an external dep in the reference, auth/store.go:90 iface area),
roles grant {READ, WRITE, READWRITE} over key ranges (interval perms cached
per user, auth/range_perm_cache.go), and every mutation bumps an
*auth revision* so tokens minted under an older ACL are rejected
(store.go's authRevision / ErrAuthOldRevision). Two token providers, as in
the reference (auth/store.go NewTokenProvider): `simple` — opaque TTL'd
random tokens held in node-local memory — and `jwt` — stateless signed
tokens carrying {username, revision, exp} claims (auth/jwt.go:28,117)
with the reference's full sign-method set (options.go:88-103):
HS256/384/512 shared-secret HMAC plus RS*/PS*/ES* PEM keypairs, and
verify-only operation when only a public key is configured.
"""
from __future__ import annotations

import base64
import dataclasses
import hashlib
import hmac
import json
import os
import secrets


class AuthError(Exception):
    pass


class ErrAuthNotEnabled(AuthError):
    pass


class ErrUserNotFound(AuthError):
    pass


class ErrUserAlreadyExist(AuthError):
    pass


class ErrRoleNotFound(AuthError):
    pass


class ErrRoleAlreadyExist(AuthError):
    pass


class ErrAuthFailed(AuthError):
    pass


class ErrPermissionDenied(AuthError):
    pass


class ErrInvalidAuthToken(AuthError):
    pass


class ErrAuthOldRevision(AuthError):
    pass


READ, WRITE, READWRITE = 0, 1, 2


def _b64url(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).rstrip(b"=").decode()


def _b64url_dec(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


_JWT_HASHES = {"256": hashlib.sha256, "384": hashlib.sha384,
               "512": hashlib.sha512}
# ES* fixed-width (r||s) coordinate sizes per curve (RFC 7518 §3.4)
_EC_COORD_BYTES = {"secp256r1": 32, "secp384r1": 48, "secp521r1": 66}
_ES_CURVE = {"ES256": "secp256r1", "ES384": "secp384r1",
             "ES512": "secp521r1"}


class JWTTokenProvider:
    """Stateless JWT provider (auth/jwt.go:28 tokenJWT).

    Sign methods mirror the reference's (auth/options.go:88-103 +
    jwt.go:152-156): HS256/384/512 (HMAC shared secret), RS*/PS* (RSA /
    RSA-PSS PEM keypair), ES* (ECDSA PEM keypair on the matching
    curve). A PUBLIC key yields a verify-only provider — it can check
    tokens minted elsewhere but not assign (jwt.go:150-160 verifyOnly).

    assign() mints {username, revision, exp} claims (jwt.go:117-127);
    info() verifies the signature + algorithm and rejects expired tokens.
    Like the reference, user deletion does NOT invalidate outstanding jwt
    tokens (tokenJWT.invalidateUser is a no-op, jwt.go:38) — revocation
    happens through the auth-revision check at permission time.
    """

    def __init__(self, key: bytes, ttl: int = 300, sign_method: str = "HS256"):
        family, bits = sign_method[:2], sign_method[2:]
        if family not in ("HS", "RS", "PS", "ES") or \
                bits not in _JWT_HASHES:
            raise AuthError(f"unsupported jwt sign method {sign_method!r}")
        if not key:
            raise AuthError("jwt token provider requires a signing key")
        self.ttl = ttl
        self.sign_method = sign_method
        self._family = family
        self._hash = _JWT_HASHES[bits]
        self.verify_only = False
        if family == "HS":
            self.key = key
            self._priv = self._pub = None
        else:
            self.key = None
            self._priv, self._pub = self._load_asym_key(key)

    def _load_asym_key(self, pem: bytes):
        """PEM private key → (priv, pub); PEM public key → (None, pub)
        for verify-only providers. Key type must match the method."""
        try:
            from cryptography.hazmat.primitives import serialization
            from cryptography.hazmat.primitives.asymmetric import ec, rsa
        except ImportError:
            raise AuthError(
                f"jwt {self.sign_method} needs the 'cryptography' "
                "package; only HS* methods work without it") from None

        priv = pub = None
        try:
            priv = serialization.load_pem_private_key(pem, password=None)
            pub = priv.public_key()
        except TypeError:
            raise AuthError(
                f"jwt {self.sign_method}: password-protected private "
                "keys are not supported") from None
        except ValueError:
            try:
                pub = serialization.load_pem_public_key(pem)
            except (ValueError, TypeError):
                raise AuthError(
                    f"jwt {self.sign_method}: key is neither a PEM "
                    "private nor public key") from None
            self.verify_only = True
        except Exception as e:  # UnsupportedAlgorithm and kin
            raise AuthError(
                f"jwt {self.sign_method}: cannot load key: {e}") from None
        want = rsa.RSAPublicKey if self._family in ("RS", "PS") \
            else ec.EllipticCurvePublicKey
        if not isinstance(pub, want):
            raise AuthError(
                f"jwt {self.sign_method} requires an "
                f"{'RSA' if self._family != 'ES' else 'ECDSA'} key")
        if self._family == "ES":
            want_curve = _ES_CURVE[self.sign_method]
            if pub.curve.name != want_curve:
                raise AuthError(
                    f"jwt {self.sign_method} requires curve "
                    f"{want_curve}, got {pub.curve.name}")
        return priv, pub

    def _crypto_hash(self):
        from cryptography.hazmat.primitives import hashes

        return {hashlib.sha256: hashes.SHA256, hashlib.sha384:
                hashes.SHA384, hashlib.sha512: hashes.SHA512}[
                    self._hash]()

    def _rsa_padding(self, for_verify: bool = False):
        from cryptography.hazmat.primitives.asymmetric import padding

        if self._family == "PS":
            h = self._crypto_hash()
            # sign with salt = digest size (RFC 7518); verify with AUTO
            # so tokens from signers using max-length salt (golang-jwt,
            # hence reference-built etcds) also pass
            salt = padding.PSS.AUTO if for_verify else h.digest_size
            return padding.PSS(mgf=padding.MGF1(h), salt_length=salt)
        return padding.PKCS1v15()

    def _sign(self, signing_input: bytes) -> bytes:
        if self._family == "HS":
            return hmac.new(self.key, signing_input, self._hash).digest()
        if self.verify_only or self._priv is None:
            raise ErrInvalidAuthToken(
                "verify-only jwt provider cannot assign tokens")
        if self._family in ("RS", "PS"):
            return self._priv.sign(signing_input, self._rsa_padding(),
                                   self._crypto_hash())
        # ES*: DER → fixed-width r||s (RFC 7518 §3.4)
        from cryptography.hazmat.primitives.asymmetric import ec, utils

        der = self._priv.sign(signing_input,
                              ec.ECDSA(self._crypto_hash()))
        r, s = utils.decode_dss_signature(der)
        n = _EC_COORD_BYTES[self._priv.curve.name]
        return r.to_bytes(n, "big") + s.to_bytes(n, "big")

    def _verify(self, signing_input: bytes, sig: bytes) -> bool:
        if self._family == "HS":
            return hmac.compare_digest(self._sign(signing_input), sig)
        from cryptography.exceptions import InvalidSignature

        try:
            if self._family in ("RS", "PS"):
                self._pub.verify(sig, signing_input,
                                 self._rsa_padding(for_verify=True),
                                 self._crypto_hash())
                return True
            from cryptography.hazmat.primitives.asymmetric import (
                ec,
                utils,
            )

            n = _EC_COORD_BYTES[self._pub.curve.name]
            if len(sig) != 2 * n:
                return False
            der = utils.encode_dss_signature(
                int.from_bytes(sig[:n], "big"),
                int.from_bytes(sig[n:], "big"))
            self._pub.verify(der, signing_input,
                             ec.ECDSA(self._crypto_hash()))
            return True
        except InvalidSignature:
            return False

    def assign(self, username: str, revision: int, now: int) -> str:
        header = _b64url(json.dumps(
            {"alg": self.sign_method, "typ": "JWT"},
            separators=(",", ":"), sort_keys=True).encode())
        claims = _b64url(json.dumps(
            {"username": username, "revision": revision,
             "exp": now + self.ttl},
            separators=(",", ":"), sort_keys=True).encode())
        signing_input = f"{header}.{claims}".encode()
        return f"{header}.{claims}.{_b64url(self._sign(signing_input))}"

    def info(self, token: str, now: int) -> tuple[str, int]:
        try:
            header_s, claims_s, sig_s = token.split(".")
            header = json.loads(_b64url_dec(header_s))
            if header.get("alg") != self.sign_method:
                raise ErrInvalidAuthToken("invalid signing method")
            if not self._verify(f"{header_s}.{claims_s}".encode(),
                                _b64url_dec(sig_s)):
                raise ErrInvalidAuthToken("bad signature")
            claims = json.loads(_b64url_dec(claims_s))
            username = claims["username"]
            revision = int(claims["revision"])
            exp = int(claims["exp"])
        except ErrInvalidAuthToken:
            raise
        except Exception:
            raise ErrInvalidAuthToken("malformed jwt token")
        if exp <= now:
            raise ErrInvalidAuthToken("expired jwt token")
        return username, revision


@dataclasses.dataclass
class Permission:
    perm_type: int
    key: bytes
    range_end: bytes | None = None

    # coverage checks live on the unified per-user interval trees
    # (AuthStore._perm_cache), not per-permission — the reference's
    # range_perm_cache merges abutting grants before checking


@dataclasses.dataclass
class User:
    name: str
    salt: bytes
    pw_hash: bytes
    roles: set[str] = dataclasses.field(default_factory=set)
    no_password: bool = False


@dataclasses.dataclass
class Role:
    name: str
    perms: list[Permission] = dataclasses.field(default_factory=list)


def _hash(password: str, salt: bytes) -> bytes:
    return hashlib.scrypt(password.encode(), salt=salt, n=2**10, r=8, p=1)


class AuthStore:
    ROOT_USER = "root"
    ROOT_ROLE = "root"
    TOKEN_TTL = 300  # simpleTokenTTL (auth/simple_token.go), in ticks here

    def __init__(self, token: str = "simple", jwt_key: bytes | None = None):
        """`token` mirrors the reference's --auth-token flag
        (auth/store.go NewTokenProvider): "simple", or
        "jwt[,sign-method=HS256][,ttl=SECONDS]" with the signing key
        supplied via `jwt_key` (the priv-key= file of the reference)."""
        self.enabled = False
        self.revision = 1
        self.users: dict[str, User] = {}
        self.roles: dict[str, Role] = {}
        # (user, write?) -> (auth_revision, unified interval tree) — the
        # rangePermCache analog, invalidated by revision movement
        self._perm_trees: dict = {}
        # token -> (username, auth_revision, expiry_tick)  [simple provider]
        self.tokens: dict[str, tuple[str, int, int]] = {}
        self.now = 0
        parts = token.split(",")
        self.token_type = parts[0]
        if self.token_type == "jwt":
            try:
                opts = dict(p.split("=", 1) for p in parts[1:] if p)
                ttl = int(opts.get("ttl", self.TOKEN_TTL))
            except ValueError as e:
                raise AuthError(f"invalid jwt token options {token!r}: {e}")
            self.jwt = JWTTokenProvider(
                key=jwt_key or b"",
                ttl=ttl,
                sign_method=opts.get("sign-method", "HS256"),
            )
        elif self.token_type == "simple":
            self.jwt = None
        else:
            raise AuthError(f"unknown token provider {self.token_type!r}")

    def tick(self, n: int = 1) -> None:
        self.now += n
        for t in [t for t, (_, _, exp) in self.tokens.items() if exp <= self.now]:
            del self.tokens[t]

    def _bump(self) -> None:
        self.revision += 1

    # -- enable/disable (store.go AuthEnable/AuthDisable) --------------------
    def auth_enable(self) -> None:
        root = self.users.get(self.ROOT_USER)
        if root is None:
            raise ErrUserNotFound("root user does not exist")
        if self.ROOT_ROLE not in root.roles:
            raise AuthError("root user does not have root role")
        self.enabled = True
        self._bump()

    def auth_disable(self) -> None:
        self.enabled = False
        self.tokens.clear()
        self._bump()

    # -- users ---------------------------------------------------------------
    def user_add(self, name: str, password: str = "", no_password: bool = False,
                 salt: bytes | None = None, pw_hash: bytes | None = None):
        """Apply-path user creation. For replicated applies the proposer
        hashes the password once and ships (salt, pw_hash) inside the entry
        — matching auth/store.go, which stores the bcrypt hash carried by
        the AuthUserAdd request — so every member (and every deterministic
        replay) produces identical auth state."""
        if name in self.users:
            raise ErrUserAlreadyExist(name)
        if salt is None:
            salt = os.urandom(16)
        if pw_hash is None:
            pw_hash = b"" if no_password else _hash(password, salt)
        self.users[name] = User(
            name, salt, b"" if no_password else pw_hash,
            no_password=no_password,
        )
        self._bump()

    def user_delete(self, name: str):
        if name == self.ROOT_USER and self.enabled:
            raise AuthError("cannot delete root user while auth is enabled")
        if name not in self.users:
            raise ErrUserNotFound(name)
        del self.users[name]
        self.tokens = {
            t: v for t, v in self.tokens.items() if v[0] != name
        }
        self._bump()

    def user_change_password(self, name: str, password: str = "",
                             salt: bytes | None = None,
                             pw_hash: bytes | None = None):
        """See user_add: (salt, pw_hash) are precomputed by the proposer for
        deterministic replicated applies."""
        u = self.users.get(name)
        if u is None:
            raise ErrUserNotFound(name)
        u.salt = salt if salt is not None else os.urandom(16)
        u.pw_hash = pw_hash if pw_hash is not None else _hash(password, u.salt)
        self._bump()

    def user_grant_role(self, name: str, role: str):
        u = self.users.get(name)
        if u is None:
            raise ErrUserNotFound(name)
        if role != self.ROOT_ROLE and role not in self.roles:
            raise ErrRoleNotFound(role)
        u.roles.add(role)
        self._bump()

    def user_revoke_role(self, name: str, role: str):
        u = self.users.get(name)
        if u is None:
            raise ErrUserNotFound(name)
        u.roles.discard(role)
        self._bump()

    # -- roles ---------------------------------------------------------------
    def role_add(self, name: str):
        if name in self.roles:
            raise ErrRoleAlreadyExist(name)
        self.roles[name] = Role(name)
        self._bump()

    def role_delete(self, name: str):
        if name == self.ROOT_ROLE:
            raise AuthError("cannot delete root role")
        if name not in self.roles:
            raise ErrRoleNotFound(name)
        del self.roles[name]
        for u in self.users.values():
            u.roles.discard(name)
        self._bump()

    def role_grant_permission(self, role: str, perm: Permission):
        r = self.roles.get(role)
        if r is None:
            raise ErrRoleNotFound(role)
        r.perms = [
            p for p in r.perms
            if (p.key, p.range_end) != (perm.key, perm.range_end)
        ] + [perm]
        self._bump()

    def role_revoke_permission(self, role: str, key: bytes, range_end=None):
        r = self.roles.get(role)
        if r is None:
            raise ErrRoleNotFound(role)
        r.perms = [
            p for p in r.perms if (p.key, p.range_end) != (key, range_end)
        ]
        self._bump()

    # -- snapshot/restore (the authBuckets content in schema/auth.go) --------
    def to_snapshot(self) -> dict:
        """Replicated auth state only — tokens are node-local and ephemeral
        (the reference's simple tokens live in memory, not the backend)."""
        return {
            "enabled": self.enabled,
            "revision": self.revision,
            "users": {
                n: {
                    "salt": u.salt,
                    "pw_hash": u.pw_hash,
                    "roles": sorted(u.roles),
                    "no_password": u.no_password,
                }
                for n, u in self.users.items()
            },
            "roles": {
                n: [
                    (p.perm_type, p.key, p.range_end) for p in r.perms
                ]
                for n, r in self.roles.items()
            },
        }

    def restore(self, snap: dict) -> None:
        self.enabled = snap["enabled"]
        self.revision = snap["revision"]
        self.users = {
            n: User(n, d["salt"], d["pw_hash"], set(d["roles"]),
                    d["no_password"])
            for n, d in snap["users"].items()
        }
        self.roles = {
            n: Role(n, [Permission(t, k, re) for t, k, re in perms])
            for n, perms in snap["roles"].items()
        }
        self.tokens.clear()
        self._perm_trees.clear()

    # -- authn (simple token provider) ---------------------------------------
    def authenticate(self, name: str, password: str) -> str:
        if not self.enabled:
            raise ErrAuthNotEnabled()
        u = self.users.get(name)
        if u is None:
            raise ErrAuthFailed()
        if not u.no_password and _hash(password, u.salt) != u.pw_hash:
            raise ErrAuthFailed()
        if self.jwt is not None:
            if self.jwt.verify_only:
                # a public-key provider can check tokens but not mint:
                # this is a server config issue, not a bad credential
                raise AuthError(
                    "jwt provider is verify-only (public key "
                    "configured): this server cannot mint tokens")
            return self.jwt.assign(name, self.revision, self.now)
        token = f"{name}.{secrets.token_hex(16)}"
        self.tokens[token] = (name, self.revision, self.now + self.TOKEN_TTL)
        return token

    # Transport-injected certificate identities: the gateway prefixes
    # the verified client-cert CN with this namespace (and strips any
    # wire-supplied "cert:" Authorization header, so only the TLS layer
    # can mint one). AuthInfoFromTLS (server/auth/store.go:985-1020):
    # the CN is the username at the CURRENT auth revision, no password.
    CERT_TOKEN_PREFIX = "cert:"

    def auth_info(self, token: str) -> tuple[str, int]:
        """(username, revision) for a live token."""
        if token.startswith(self.CERT_TOKEN_PREFIX):
            return token[len(self.CERT_TOKEN_PREFIX):], self.revision
        if self.jwt is not None:
            return self.jwt.info(token, self.now)
        v = self.tokens.get(token)
        if v is None:
            raise ErrInvalidAuthToken()
        name, rev, exp = v
        if exp <= self.now:
            del self.tokens[token]
            raise ErrInvalidAuthToken()
        return name, rev

    # -- authz (store.go IsPutPermitted/IsRangePermitted + range_perm_cache) -
    def check(self, token: str, key: bytes, range_end=None, write=False):
        if not self.enabled:
            return
        name, rev = self.auth_info(token)
        if rev < self.revision:
            raise ErrAuthOldRevision()
        self.check_user(name, key, range_end, write)

    def check_user(self, name: str, key: bytes, range_end=None, write=False):
        if not self.enabled:
            return
        u = self.users.get(name)
        if u is None:
            raise ErrUserNotFound(name)
        if self.ROOT_ROLE in u.roles:
            return
        tree = self._perm_cache(name, write)
        try:
            want = self._req_interval(key, range_end)
        except ValueError:
            # degenerate request range (range_end <= key): nothing can
            # grant it — deny, don't propagate adt's construction error
            raise ErrPermissionDenied(name)
        # checkKeyInterval over UNIFIED ranges (range_perm_cache.go:
        # 104-120): a request spanning several abutting grants passes —
        # per-permission containment would wrongly deny it
        if tree.contains(want):
            return
        raise ErrPermissionDenied(name)

    @staticmethod
    def _req_interval(key: bytes, range_end):
        from etcd_tpu.utils import adt

        if range_end is None:
            return adt.point(key)
        if range_end == b"\x00":
            return adt.Interval(key, adt.INF)
        return adt.Interval(key, range_end)

    def _perm_cache(self, name: str, write: bool):
        """Per-(user, op) unified interval tree, rebuilt when the auth
        revision moves (rangePermCache + invalidation on any auth
        mutation, range_perm_cache.go:24-60)."""
        from etcd_tpu.utils import adt

        cached = self._perm_trees.get((name, write))
        if cached is not None and cached[0] == self.revision:
            return cached[1]
        tree = adt.IntervalTree()
        u = self.users.get(name)
        want = WRITE if write else READ
        for rname in (u.roles if u else ()):
            r = self.roles.get(rname)
            if r is None:
                continue
            for p in r.perms:
                if p.perm_type != READWRITE and p.perm_type != want:
                    continue
                try:
                    tree.insert(self._req_interval(p.key, p.range_end), p)
                except ValueError:
                    # a degenerate stored grant (role_grant_permission
                    # does no validation) must not break every authz
                    # check for the user — it simply grants nothing
                    continue
        self._perm_trees[(name, write)] = (self.revision, tree)
        return tree

    def is_admin(self, token: str) -> None:
        if not self.enabled:
            return
        name, rev = self.auth_info(token)
        if rev < self.revision:
            raise ErrAuthOldRevision()
        u = self.users.get(name)
        if u is None:
            raise ErrUserNotFound(name)
        if self.ROOT_ROLE not in u.roles:
            raise ErrPermissionDenied(name)
