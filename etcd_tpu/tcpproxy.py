"""L4 TCP gateway — server/proxy/tcpproxy parity (the `etcd gateway`
command, etcdmain/gateway.go).

The reference's TCPProxy (proxy/tcpproxy/userspace.go) accepts TCP
connections and forwards raw bytes to one of a set of backend endpoints:
round-robin pick, dead endpoints marked inactive and retried after a
monitor interval, SRV-weighted remotes treated as a flat list here (the
weights only matter with DNS SRV priorities, srv.py).
"""
from __future__ import annotations

import socket
import threading
import time


class Remote:
    """One backend endpoint (userspace.go `remote`): address + liveness."""

    def __init__(self, host: str, port: int):
        self.host, self.port = host, port
        self.inactive = False
        self._mu = threading.Lock()

    def inactivate(self) -> None:
        with self._mu:
            self.inactive = True

    def is_active(self) -> bool:
        with self._mu:
            return not self.inactive

    def try_reactivate(self) -> bool:
        """Dial-and-close probe (userspace.go tryReactivate)."""
        try:
            with socket.create_connection((self.host, self.port), timeout=1):
                pass
        except OSError:
            return False
        with self._mu:
            self.inactive = False
        return True


class TCPProxy:
    """userspace.go TCPProxy: serve(), pick(), io pump per connection."""

    def __init__(self, endpoints: list[tuple[str, int]],
                 host: str = "127.0.0.1", port: int = 0,
                 monitor_interval: float = 5.0):
        self.remotes = [Remote(h, p) for h, p in endpoints]
        self._rr = 0
        self._mu = threading.Lock()
        self.monitor_interval = monitor_interval
        self._listener = socket.create_server((host, port))
        self.host, self.port = self._listener.getsockname()[:2]
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- endpoint pick (round-robin over active remotes) ---------------------
    def pick(self) -> Remote | None:
        with self._mu:
            n = len(self.remotes)
            for i in range(n):
                r = self.remotes[(self._rr + i) % n]
                if r.is_active():
                    self._rr = (self._rr + i + 1) % n
                    return r
        return None

    # -- serving -------------------------------------------------------------
    def start(self) -> "TCPProxy":
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        m = threading.Thread(target=self._monitor_loop, daemon=True)
        m.start()
        self._threads.append(m)
        return self

    def _accept_loop(self) -> None:
        self._listener.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        """Forward one client connection to the first dialable remote
        (userspace.go serve: try picks until one dials, inactivating
        failures)."""
        backend = None
        for _ in range(len(self.remotes)):
            r = self.pick()
            if r is None:
                break
            try:
                backend = socket.create_connection((r.host, r.port),
                                                   timeout=2)
                break
            except OSError:
                r.inactivate()
        if backend is None:
            conn.close()
            return
        a = threading.Thread(target=self._pump, args=(conn, backend),
                             daemon=True)
        b = threading.Thread(target=self._pump, args=(backend, conn),
                             daemon=True)
        a.start()
        b.start()

    @staticmethod
    def _pump(src: socket.socket, dst: socket.socket) -> None:
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass

    def _monitor_loop(self) -> None:
        """runMonitor (userspace.go): periodically re-probe inactive
        remotes."""
        while not self._stop.wait(self.monitor_interval):
            for r in self.remotes:
                if not r.is_active():
                    r.try_reactivate()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
