"""pkg/adt parity: byte-affine intervals + an interval tree.

The reference implements a red-black interval tree
(pkg/adt/interval_tree.go) keyed by ``[begin, end)`` byte intervals with
an affine "infinite" endpoint for ``>= key`` ranges, consumed by the
auth range-permission cache (server/auth/range_perm_cache.go) and lease
checkpointing. The balancing strategy is an implementation detail; this
analog keeps the begin-sorted list + bisect (the stores here hold tens
of permissions, not millions of watch ranges) while matching the API
surface and semantics: Insert/Delete/Find/Intersects/Visit, plus the
coverage queries the auth cache is built on — ``contains`` is true when
the UNION of stored intervals covers the queried one, exactly
checkKeyInterval's walk over unified ranges.
"""
from __future__ import annotations

import bisect
import dataclasses


class _AffineInf:
    """The +inf endpoint (adt.BytesAffineComparable end sentinel)."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "INF"


INF = _AffineInf()


def _le(a, b) -> bool:
    if a is INF:
        return b is INF
    if b is INF:
        return True
    return a <= b


def _lt(a, b) -> bool:
    return _le(a, b) and not (a is b or a == b)


@dataclasses.dataclass(frozen=True)
class Interval:
    """[begin, end); end may be INF (NewBytesAffineInterval with an
    all-0xff-free open end, adt/interval_tree.go:37-57)."""

    begin: bytes
    end: object  # bytes | INF

    def __post_init__(self):
        if self.end is not INF and not _lt(self.begin, self.end):
            raise ValueError(f"empty interval [{self.begin!r}, {self.end!r})")


def point(key: bytes) -> Interval:
    """NewBytesAffinePoint: [key, key+0x00)."""
    return Interval(key, key + b"\x00")


class IntervalTree:
    """Begin-sorted interval store with the adt.IntervalTree queries."""

    def __init__(self):
        self._begins: list[bytes] = []
        self._items: list[tuple[Interval, object]] = []

    def __len__(self) -> int:
        return len(self._items)

    def insert(self, ivl: Interval, val=None) -> None:
        i = bisect.bisect_left(self._begins, ivl.begin)
        self._begins.insert(i, ivl.begin)
        self._items.insert(i, (ivl, val))

    def delete(self, ivl: Interval) -> bool:
        for i, (stored, _) in enumerate(self._items):
            if stored == ivl:
                del self._begins[i]
                del self._items[i]
                return True
        return False

    def find(self, ivl: Interval):
        """Exact-interval lookup -> value (None if absent)."""
        for stored, val in self._items:
            if stored == ivl:
                return val
        return None

    def visit(self, ivl: Interval, fn) -> None:
        """Call fn(stored, val) for every stored interval intersecting
        ivl; stop early when fn returns False (adt nodeVisitor)."""
        for stored, val in self._items:
            if _lt(ivl.begin, stored.end) and _lt(stored.begin, ivl.end):
                if fn(stored, val) is False:
                    return

    def intersects(self, ivl: Interval) -> bool:
        found = False

        def f(stored, val):
            nonlocal found
            found = True
            return False

        self.visit(ivl, f)
        return found

    def contains(self, ivl: Interval) -> bool:
        """True iff the UNION of stored intervals covers ivl — the walk
        range_perm_cache.go:104-120 (checkKeyInterval) does over unified
        ranges: advance a cursor through overlapping intervals until the
        queried end is reached or a gap appears."""
        cursor = ivl.begin
        while True:
            best = None
            for stored, _ in self._items:
                if _le(stored.begin, cursor) and _lt(cursor, stored.end):
                    if best is None or _lt(best, stored.end):
                        best = stored.end
            if best is None:
                return False
            if _le(ivl.end, best):
                return True
            cursor = best

    def union(self) -> list[Interval]:
        """Merged (unified) intervals, begin-sorted."""
        out: list[Interval] = []
        for stored, _ in self._items:
            if out and _le(stored.begin, out[-1].end):
                if _lt(out[-1].end, stored.end):
                    out[-1] = Interval(out[-1].begin, stored.end)
            else:
                out.append(Interval(stored.begin, stored.end))
        return out
