"""v2 API emulated on the v3 MVCC store (api/v2v3 analog): depth-encoded
keys, dir markers, txn-guarded CAS/CAD, action-key watch recovery."""
import pytest

from etcd_tpu.server.kvserver import EtcdCluster
from etcd_tpu.server.v2store import (
    EcodeDirNotEmpty,
    EcodeKeyNotFound,
    EcodeNodeExist,
    EcodeNotFile,
    EcodeTestFailed,
    V2Error,
)
from etcd_tpu.server.v2v3 import V2v3Store, mk_v2_rev, mk_v3_rev


@pytest.fixture(scope="module")
def ec():
    c = EtcdCluster(n_members=3)
    c.ensure_leader()
    return c


@pytest.fixture()
def s(ec):
    st = V2v3Store(ec, pfx="/__v2")
    # fresh namespace per test: drop everything under the prefix
    try:
        st.delete("/t", recursive=True)
    except V2Error:
        pass
    return st


def test_rev_mapping():
    assert mk_v2_rev(0) == 0 and mk_v2_rev(5) == 4
    assert mk_v3_rev(0) == 0 and mk_v3_rev(4) == 5


def test_set_get_roundtrip(s):
    e = s.set("/t/foo", value="bar")
    assert e.action == "set"
    g = s.get("/t/foo")
    assert g.node["value"] == "bar"
    assert g.node["createdIndex"] > 0
    # replace keeps v2 semantics: new mod index, prevNode reported
    e2 = s.set("/t/foo", value="baz")
    assert e2.prev_node["value"] == "bar"
    assert e2.node["modifiedIndex"] > e.node["modifiedIndex"]


def test_get_missing(s):
    with pytest.raises(V2Error) as ei:
        s.get("/t/nope")
    assert ei.value.code == EcodeKeyNotFound


def test_create_semantics(s):
    e = s.create("/t/c", value="v1")
    assert e.action == "create"
    with pytest.raises(V2Error) as ei:
        s.create("/t/c", value="v2")
    assert ei.value.code == EcodeNodeExist


def test_update_requires_existing(s):
    with pytest.raises(V2Error) as ei:
        s.update("/t/u", "v")
    assert ei.value.code == EcodeKeyNotFound
    s.set("/t/u", value="v1")
    e = s.update("/t/u", "v2")
    assert e.action == "update"
    assert e.prev_node["value"] == "v1"
    assert e.node["createdIndex"] == e.prev_node["createdIndex"]


def test_cas_cad(s):
    s.set("/t/k", value="v1")
    with pytest.raises(V2Error) as ei:
        s.compare_and_swap("/t/k", "bad", 0, "v2")
    assert ei.value.code == EcodeTestFailed
    e = s.compare_and_swap("/t/k", "v1", 0, "v2")
    assert e.action == "compareAndSwap"
    idx = e.node["modifiedIndex"]
    e = s.compare_and_swap("/t/k", "", idx, "v3")
    assert e.node["value"] == "v3"
    with pytest.raises(V2Error):
        s.compare_and_delete("/t/k", "wrong", 0)
    e = s.compare_and_delete("/t/k", "v3", 0)
    assert e.action == "compareAndDelete"
    with pytest.raises(V2Error):
        s.get("/t/k")


def test_dirs_implicit_and_markers(s):
    s.set("/t/d/a", value="1")
    s.set("/t/d/b", value="2")
    g = s.get("/t/d", sorted_=True)
    assert g.node["dir"] is True
    assert [n["value"] for n in g.node["nodes"]] == ["1", "2"]
    # explicit empty dir via marker
    s.create("/t/empty", dir=True)
    g = s.get("/t/empty")
    assert g.node["dir"] is True and g.node["nodes"] == []
    # a dir is not a file
    with pytest.raises(V2Error) as ei:
        s.set("/t/d", value="x")
    assert ei.value.code == EcodeNotFile


def test_recursive_listing(s):
    s.set("/t/r/x", value="1")
    s.set("/t/r/sub/y", value="2")
    g = s.get("/t/r", recursive=True, sorted_=True)
    keys = [n["key"] for n in g.node["nodes"]]
    assert keys == ["/t/r/sub", "/t/r/x"]
    sub = g.node["nodes"][0]
    assert sub["nodes"][0]["key"] == "/t/r/sub/y"
    # non-recursive shows the sub dir without children
    g = s.get("/t/r", sorted_=True)
    assert "nodes" not in g.node["nodes"][0] or \
        not g.node["nodes"][0].get("nodes")


def test_delete_dir_rules(s):
    s.set("/t/dd/k", value="v")
    with pytest.raises(V2Error) as ei:
        s.delete("/t/dd")
    assert ei.value.code == EcodeNotFile
    with pytest.raises(V2Error) as ei:
        s.delete("/t/dd", dir=True)
    assert ei.value.code == EcodeDirNotEmpty
    e = s.delete("/t/dd", recursive=True)
    assert e.node["dir"] is True
    with pytest.raises(V2Error):
        s.get("/t/dd/k")


def test_create_in_order(s):
    e1 = s.create("/t/q", unique=True, value="a")
    e2 = s.create("/t/q", unique=True, value="b")
    assert e1.node["key"] < e2.node["key"]
    g = s.get("/t/q", sorted_=True)
    assert [n["value"] for n in g.node["nodes"]] == ["a", "b"]


def test_hidden_nodes_skipped(s):
    s.set("/t/h/_secret", value="x")
    s.set("/t/h/vis", value="y")
    g = s.get("/t/h", sorted_=True)
    assert [n["key"] for n in g.node["nodes"]] == ["/t/h/vis"]


def test_watch_action_recovery(s):
    w = s.watch("/t/w", recursive=True)
    s.set("/t/w/a", value="1")
    ev = w.next()
    assert ev is not None
    assert ev.action == "set"
    assert ev.node["key"] == "/t/w/a"
    s.compare_and_swap("/t/w/a", "1", 0, "2")
    ev = w.next()
    assert ev.action == "compareAndSwap"
    assert ev.prev_node["value"] == "1"
    s.delete("/t/w/a")
    ev = w.next()
    assert ev.action == "delete"
    w.remove()


def test_v2v3_state_is_replicated(ec, s):
    s.set("/t/rep", value="v")
    ec.stabilize()
    hashes = {ec.hash_kv(m) for m in range(3)}
    assert len(hashes) == 1  # same v3 store everywhere
