"""Watch API parity: event filters, progress notification and response
fragmentation (server/etcdserver/api/v3rpc/watch.go:135-143 stream flags,
:303-305 fragment, :339-345 WatchProgressRequest, :565-583
FiltersFromRequest; mvcc watchStream.RequestProgress semantics).
"""
import pytest

from etcd_tpu.server.kvserver import EtcdCluster
from etcd_tpu.server.mvcc import MVCCStore
from etcd_tpu.server.watch import WatchableStore


# ---------------------------------------------------------------------------
# store-level: filters + progress
# ---------------------------------------------------------------------------

def test_filter_noput_nodelete_live_path():
    """filterNoPut/filterNoDelete on the synced notify path."""
    ws = WatchableStore()
    w_nop = ws.watch(b"k", filters=("put",))      # NOPUT
    w_nod = ws.watch(b"k", filters=("delete",))   # NODELETE
    w_all = ws.watch(b"k")
    for i in range(4):
        txn = ws.kv.write_txn()
        if i % 2 == 0:
            txn.put(b"k", b"v%d" % i)
        else:
            txn.delete_range(b"k")
        txn.end()
        ws.notify(txn.events)
    assert [e.type for e in ws.take_events(w_nop.id)] == ["delete", "delete"]
    assert [e.type for e in ws.take_events(w_nod.id)] == ["put", "put"]
    assert [e.type for e in ws.take_events(w_all.id)] == [
        "put", "delete", "put", "delete"
    ]
    # filtered watchers stayed synced (start_rev advanced past every event)
    assert ws.synced[w_nop.id].start_rev == ws.kv.current_rev + 1


def test_filter_applies_to_history_catchup():
    """Filters also apply on the unsynced/catch-up read (kvsToEvents)."""
    ws = WatchableStore()
    for i in range(3):
        txn = ws.kv.write_txn()
        txn.put(b"k", b"v%d" % i)
        txn.end()
        ws.notify(txn.events)
    txn = ws.kv.write_txn()
    txn.delete_range(b"k")
    txn.end()
    ws.notify(txn.events)
    w = ws.watch(b"k", start_rev=1, filters=("put",))
    assert w.id in ws.unsynced
    ws.sync_watchers()
    evs = ws.take_events(w.id)
    assert [e.type for e in evs] == ["delete"]
    assert w.id in ws.synced


def test_progress_only_when_synced():
    """mvcc RequestProgress: progress is reported only for a synced,
    fully-drained watcher — otherwise the header would overclaim."""
    ws = WatchableStore()
    w = ws.watch(b"k")
    assert ws.progress(w.id) == ws.kv.current_rev
    txn = ws.kv.write_txn()
    txn.put(b"k", b"x")
    txn.end()
    ws.notify(txn.events)
    assert ws.progress(w.id) is None  # undrained events pending
    ws.take_events(w.id)
    assert ws.progress(w.id) == ws.kv.current_rev
    # an unsynced (catching-up) watcher reports no progress
    w2 = ws.watch(b"k", start_rev=1)
    assert ws.progress(w2.id) is None


def test_take_events_limit_fragments_buffer():
    ws = WatchableStore()
    w = ws.watch(b"k", fragment=True)
    for i in range(5):
        txn = ws.kv.write_txn()
        txn.put(b"k", b"v%d" % i)
        txn.end()
        ws.notify(txn.events)
    first = ws.take_events(w.id, limit=2)
    assert [e.kv.value for e in first] == [b"v0", b"v1"]
    assert ws.pending_events(w.id) == 3
    rest = ws.take_events(w.id)
    assert [e.kv.value for e in rest] == [b"v2", b"v3", b"v4"]
    assert ws.pending_events(w.id) == 0


# ---------------------------------------------------------------------------
# server + client level
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def ec():
    ec = EtcdCluster()
    ec.ensure_leader()
    return ec


def test_client_watch_filters_and_progress(ec):
    from etcd_tpu.client import Client

    cli = Client(ec)
    w = cli.watch(b"f/", range_end=b"f0", filters=("put",),
                  progress_notify=True)
    cli.put(b"f/1", b"a")
    cli.put(b"f/2", b"b")
    cli.delete(b"f/1")
    assert [e.type for e in w.events()] == ["delete"]
    # drained + synced => RequestProgress yields the current revision
    rev = w.request_progress()
    assert rev == ec.members[ec.ensure_leader()].store.kv.current_rev


def test_gateway_watch_fragment_and_progress(ec):
    """Long-poll gateway: fragment=True splits an oversized batch into
    fragment-marked responses (sendFragments, watch.go:508-545), and an
    idle progress_notify watcher gets a bare revision header."""
    from etcd_tpu.server.v3rpc import V3Api, _b64

    srv = V3Api(ec)
    create = srv.watch({"create_request": {
        "key": _b64(b"g/"), "range_end": _b64(b"g0"),
        "fragment": True, "progress_notify": True,
    }})
    wid = create["watch_id"]
    for i in range(6):
        ec.put(b"g/%d" % i, b"x" * 50)
    ec.stabilize()
    got, frags, polls = [], 0, 0
    while True:
        r = srv.watch({"poll_request": {
            "watch_id": wid, "max_response_bytes": 200,
        }})
        polls += 1
        got += [e["kv"] for e in r["events"]]
        if r.get("fragment"):
            frags += 1
            assert r["events"], "fragments must carry events"
        else:
            break
        assert polls < 20
    assert len(got) == 6
    assert frags >= 2  # 6 events * >100B events vs 200B budget
    # the final (non-fragment) response completed the batch
    # idle poll now reports progress
    r = srv.watch({"poll_request": {"watch_id": wid}})
    assert r["events"] == []
    assert r.get("progress_notify") is True
    assert int(r["header"]["revision"]) == \
        ec.members[ec.ensure_leader()].store.kv.current_rev
    # stream-level WatchProgressRequest: watch_id -1 broadcast semantics
    pr = srv.watch({"progress_request": {}})
    assert pr["watch_id"] == "-1"
    assert int(pr["header"]["revision"]) >= 1


def test_gateway_watch_filters(ec):
    from etcd_tpu.server.v3rpc import V3Api, _b64

    srv = V3Api(ec)
    create = srv.watch({"create_request": {
        "key": _b64(b"h/"), "range_end": _b64(b"h0"),
        "filters": ["NOPUT"],
    }})
    wid = create["watch_id"]
    ec.put(b"h/1", b"a")
    ec.delete_range(b"h/1")
    ec.stabilize()
    r = srv.watch({"poll_request": {"watch_id": wid}})
    assert [e["type"] for e in r["events"]] == ["DELETE"]
