"""Host-side validating config Changer — set semantics + invariants.

The device path applies conf changes unconditionally as mask algebra
(etcd_tpu/models/confchange.py) because the reference's raft core panics on
invalid post-commit changes (raft/raft.go:1623-1643): *validation happens at
proposal time*. This module is that proposal-time validator — a faithful
re-expression of ``confchange.Changer`` (raft/confchange/confchange.go:
EnterJoint:50, LeaveJoint:91, Simple:127, apply:150, makeVoter:178,
makeLearner:207, remove:231, initProgress:245, checkInvariants:276) over
Python sets, used by the server layer before encoding a change for the
device, and by the datadriven replay of confchange/testdata/*.txt.

Error strings match the reference so golden error cases replay verbatim.
"""
from __future__ import annotations

import dataclasses

from etcd_tpu.types import (
    CC_ADD_LEARNER,
    CC_ADD_NODE,
    CC_REMOVE_NODE,
    CC_UPDATE_NODE,
)


class ConfChangeError(ValueError):
    pass


@dataclasses.dataclass
class Config:
    """tracker.Config as id sets (ids are opaque ints; the device layer maps
    them to member slots)."""

    voters: set[int] = dataclasses.field(default_factory=set)        # incoming
    voters_outgoing: set[int] = dataclasses.field(default_factory=set)
    learners: set[int] = dataclasses.field(default_factory=set)
    learners_next: set[int] = dataclasses.field(default_factory=set)
    auto_leave: bool = False
    # ids with a Progress entry; IsLearner flags (the slice of ProgressMap
    # state that the invariants constrain)
    progress: set[int] = dataclasses.field(default_factory=set)
    progress_learner: set[int] = dataclasses.field(default_factory=set)

    @property
    def joint(self) -> bool:
        return len(self.voters_outgoing) > 0

    def clone(self) -> "Config":
        return Config(
            set(self.voters), set(self.voters_outgoing), set(self.learners),
            set(self.learners_next), self.auto_leave, set(self.progress),
            set(self.progress_learner),
        )


def check_invariants(cfg: Config) -> None:
    """confchange.go:276-334."""
    for ids in (cfg.voters | cfg.voters_outgoing, cfg.learners, cfg.learners_next):
        for id_ in ids:
            if id_ not in cfg.progress:
                raise ConfChangeError(f"no progress for {id_}")
    for id_ in cfg.learners_next:
        if id_ not in cfg.voters_outgoing:
            raise ConfChangeError(f"{id_} is in LearnersNext, but not Voters[1]")
        if id_ in cfg.progress_learner:
            raise ConfChangeError(
                f"{id_} is in LearnersNext, but is already marked as learner"
            )
    for id_ in cfg.learners:
        if id_ in cfg.voters_outgoing:
            raise ConfChangeError(f"{id_} is in Learners and Voters[1]")
        if id_ in cfg.voters:
            raise ConfChangeError(f"{id_} is in Learners and Voters[0]")
        if id_ not in cfg.progress_learner:
            raise ConfChangeError(
                f"{id_} is in Learners, but is not marked as learner"
            )
    if not cfg.joint:
        if cfg.learners_next:
            raise ConfChangeError("cfg.LearnersNext must be nil when not joint")
        if cfg.auto_leave:
            raise ConfChangeError("AutoLeave must be false when not joint")


class Changer:
    """Stateless validator: methods return a NEW validated Config or raise
    ConfChangeError (the caller swaps it in only after the entry commits)."""

    def __init__(self, cfg: Config):
        self.cfg = cfg

    # -- public ops ---------------------------------------------------------
    def enter_joint(self, auto_leave: bool, ccs) -> Config:
        cfg = self._check_and_copy()
        if cfg.joint:
            raise ConfChangeError("config is already joint")
        if not cfg.voters:
            raise ConfChangeError("can't make a zero-voter config joint")
        cfg.voters_outgoing = set(cfg.voters)
        self._apply(cfg, ccs)
        cfg.auto_leave = auto_leave
        check_invariants(cfg)
        return cfg

    def leave_joint(self) -> Config:
        cfg = self._check_and_copy()
        if not cfg.joint:
            raise ConfChangeError("can't leave a non-joint config")
        for id_ in cfg.learners_next:
            cfg.learners.add(id_)
            cfg.progress_learner.add(id_)
        cfg.learners_next = set()
        for id_ in cfg.voters_outgoing:
            if id_ not in cfg.voters and id_ not in cfg.learners:
                cfg.progress.discard(id_)
                cfg.progress_learner.discard(id_)
        cfg.voters_outgoing = set()
        cfg.auto_leave = False
        check_invariants(cfg)
        return cfg

    def simple(self, ccs) -> Config:
        cfg = self._check_and_copy()
        if cfg.joint:
            raise ConfChangeError("can't apply simple config change in joint config")
        self._apply(cfg, ccs)
        if len(self.cfg.voters ^ cfg.voters) > 1:
            raise ConfChangeError(
                "more than one voter changed without entering joint config"
            )
        check_invariants(cfg)
        return cfg

    # -- internals ----------------------------------------------------------
    def _check_and_copy(self) -> Config:
        cfg = self.cfg.clone()
        check_invariants(cfg)
        return cfg

    def _apply(self, cfg: Config, ccs) -> None:
        for op, id_ in ccs:
            if id_ == 0:
                # zeroed changes are "refused upstream" markers (apply:155)
                continue
            if op == CC_ADD_NODE:
                self._make_voter(cfg, id_)
            elif op == CC_ADD_LEARNER:
                self._make_learner(cfg, id_)
            elif op == CC_REMOVE_NODE:
                self._remove(cfg, id_)
            elif op == CC_UPDATE_NODE:
                pass
            else:
                raise ConfChangeError(f"unexpected conf type {op}")
        if not cfg.voters:
            raise ConfChangeError("removed all voters")

    def _make_voter(self, cfg: Config, id_: int) -> None:
        if id_ not in cfg.progress:
            cfg.voters.add(id_)
            cfg.progress.add(id_)
            return
        cfg.progress_learner.discard(id_)
        cfg.learners.discard(id_)
        cfg.learners_next.discard(id_)
        cfg.voters.add(id_)

    def _make_learner(self, cfg: Config, id_: int) -> None:
        if id_ not in cfg.progress:
            cfg.learners.add(id_)
            cfg.progress.add(id_)
            cfg.progress_learner.add(id_)
            return
        if id_ in cfg.progress_learner:
            return
        self._remove(cfg, id_)
        cfg.progress.add(id_)  # ...but save the Progress (makeLearner:221)
        if id_ in cfg.voters_outgoing:
            cfg.learners_next.add(id_)
        else:
            cfg.progress_learner.add(id_)
            cfg.learners.add(id_)

    def _remove(self, cfg: Config, id_: int) -> None:
        if id_ not in cfg.progress:
            return
        cfg.voters.discard(id_)
        cfg.learners.discard(id_)
        cfg.learners_next.discard(id_)
        if id_ not in cfg.voters_outgoing:
            cfg.progress.discard(id_)
            cfg.progress_learner.discard(id_)


def restore(conf_state) -> Config:
    """confchange/restore.go:26-155 — rebuild a Config from a snapshot's
    ConfState by replaying synthesized single changes: first build the
    outgoing config as if it were the active one, then EnterJoint with the
    delta to the incoming one. conf_state: object with voters /
    voters_outgoing / learners / learners_next id-lists + auto_leave."""
    cs = conf_state
    out = [(CC_ADD_NODE, i) for i in cs.voters_outgoing]
    inc = (
        [(CC_REMOVE_NODE, i) for i in cs.voters_outgoing]
        + [(CC_ADD_NODE, i) for i in cs.voters]
        + [(CC_ADD_LEARNER, i) for i in cs.learners]
        + [(CC_ADD_LEARNER, i) for i in cs.learners_next]
    )
    cfg = Config()
    if not out:
        for cc in inc:
            cfg = Changer(cfg).simple([cc])
    else:
        for cc in out:
            cfg = Changer(cfg).simple([cc])
        cfg = Changer(cfg).enter_joint(cs.auto_leave, inc)
    return cfg
