"""Log replication and commit rules — raft_paper_test.go §5.3/§5.4 analogs:

  TestLeaderStartReplication / TestLeaderCommitEntry /
  TestLeaderAcknowledgeCommit / TestLeaderCommitPrecedingEntries /
  TestFollowerCommitEntry / TestLeaderSyncFollowerLog (divergent tails) /
  TestLeaderOnlyCommitsLogFromCurrentTerm, plus the KV_HASH-style
  applied-state equality checker from tests/functional.
"""
import numpy as np
import pytest

from etcd_tpu.harness.cluster import Cluster
from etcd_tpu.types import MSG_VOTE_RESP, NONE_ID, ROLE_LEADER, Spec


def applied_consistent(cl, c: int = 0):
    """Functional-tester KV_HASH analog: equal applied => equal hash chain."""
    s = cl.s
    applied = np.asarray(s.applied[..., c])
    hashes = np.asarray(s.applied_hash[..., c])
    by_applied = {}
    for m in range(applied.shape[0]):
        by_applied.setdefault(int(applied[m]), set()).add(int(hashes[m]))
    return all(len(v) == 1 for v in by_applied.values())


def test_leader_start_replication_and_commit():
    """§5.3: accepted proposals replicate, commit once a quorum acks, and
    followers learn the commit index (TestLeaderCommitEntry)."""
    cl = Cluster(n_members=3)
    cl.campaign(0)
    cl.stabilize()
    cl.propose(0, 101)
    cl.propose(0, 102)
    cl.stabilize()
    assert cl.commits().tolist() == [3, 3, 3]
    want = [(1, 0), (1, 101), (1, 102)]
    for m in range(3):
        assert cl.log_entries(m) == want
    assert cl.leaf("applied").tolist() == [3, 3, 3]
    assert applied_consistent(cl)


def test_proposal_forwarding():
    """MsgProp at a follower is forwarded to the leader (raft.go:1423-1432;
    TestProposalByProxy)."""
    cl = Cluster(n_members=3)
    cl.campaign(0)
    cl.stabilize()
    cl.propose(1, 55)  # proposed at follower 1
    cl.stabilize()
    assert cl.commits().tolist() == [2, 2, 2]
    assert cl.log_entries(2)[-1] == (1, 55)


def test_proposal_dropped_without_leader():
    """TestProposal: proposing with no leader drops the proposal."""
    cl = Cluster(n_members=3)
    cl.propose(0, 9)
    cl.stabilize()
    assert cl.commits().tolist() == [0, 0, 0]
    for m in range(3):
        assert cl.log_entries(m) == []


def test_leader_commit_preceding_entries():
    """§5.4: a new leader commits its predecessors' entries by committing an
    entry of its own term (TestLeaderCommitPrecedingEntries)."""
    cl = Cluster(n_members=3)
    cl.campaign(0)
    cl.stabilize()
    cl.propose(0, 7)
    cl.stabilize()
    # leader 1 takes over; its empty entry at term 2 commits everything
    cl.isolate(0)
    cl.campaign(1)
    cl.stabilize()
    cl.recover()
    cl.stabilize(tick=True)
    cl2 = cl  # alias
    lead = cl2.leader()
    assert lead == 1
    assert min(cl2.commits()) >= 3  # [empty t1, 7, empty t2]
    assert applied_consistent(cl2)


def test_leader_only_commits_current_term():
    """§5.4.2 (TestLeaderOnlyCommitsLogFromCurrentTerm): entries from prior
    terms are never committed by counting replicas alone."""
    cl = Cluster(n_members=5, spec=Spec(M=5))
    cl.campaign(0)
    cl.stabilize()
    # entry only reaches node 1 (partition 0,1 | 2,3,4)
    cl.partition([[0, 1], [2, 3, 4]])
    cl.propose(0, 66)
    cl.stabilize()
    assert int(cl.commits()[0]) == 1  # 66 at index 2 not committed
    # heal; 0 remains leader (higher... no: 2/3/4 may elect). Force: no new
    # election happened (no ticks), so 0 is still the only leader.
    cl.recover()
    cl.stabilize(tick=True)
    # eventually index 2 commits — but only after a current-term entry lands
    assert min(cl.commits()) >= 2
    assert applied_consistent(cl)


def test_divergent_tail_overwritten():
    """§5.3 fig.7 flavor (TestLeaderSyncFollowerLog): a follower's divergent
    uncommitted tail is truncated to match the leader."""
    cl = Cluster(n_members=3)
    cl.campaign(0)
    cl.stabilize()
    # 0 accepts proposals that never replicate (isolated with them)
    cl.isolate(0)
    cl.propose(0, 11)
    cl.propose(0, 12)
    cl.stabilize()
    assert cl.log_entries(0) == [(1, 0), (1, 11), (1, 12)]
    # new leader at term 2 with its own entries
    cl.campaign(1)
    cl.stabilize()
    assert cl.leader() == 1
    cl.propose(1, 21)
    cl.stabilize()
    # heal: 0 rejoins, hears term-2 appends, truncates 11/12
    cl.recover()
    cl.stabilize(tick=True)
    logs = [cl.log_entries(m) for m in range(3)]
    assert logs[0] == logs[1] == logs[2]
    assert (2, 21) in logs[0]
    assert (1, 11) not in logs[0]
    assert applied_consistent(cl)


def test_heartbeat_maintains_leadership_and_commit():
    """Heartbeats carry min(match, commit) (raft.go:495-511) and reset
    follower election timers (TestFollowerUpdateTermFromMessage flavor)."""
    cl = Cluster(n_members=3)
    cl.campaign(0)
    cl.stabilize()
    cl.propose(0, 5)
    cl.stabilize()
    # many ticks: leader heartbeats keep followers from campaigning
    for _ in range(25):
        cl.step(tick=True)
    assert cl.leader() == 0
    assert cl.terms().tolist() == [1, 1, 1]


def test_tick_based_election_fires():
    """With no leader, some node times out and wins (randomized timeouts in
    [T, 2T-1], raft.go:1714-1720)."""
    cl = Cluster(n_members=3)
    for _ in range(60):
        cl.step(tick=True)
        if cl.leader() != NONE_ID:
            break
    assert cl.leader() != NONE_ID
    # exactly one leader at the max term
    assert len(cl.leaders()) == 1


LEADER_TERMS = [1, 1, 1, 4, 4, 5, 5, 6, 6, 6]  # terms at indexes 1..10

# The six follower logs of Raft paper figure 7 (terms at indexes 1..n),
# exactly the table in TestLeaderSyncFollowerLog (raft_paper_test.go:695-748):
# (a) missing the last entry, (b) truncated at 4, (c) one extra term-6 entry,
# (d) two extra term-7 entries, (e) divergent term-4 tail, (f) divergent
# term-2/3 tail.
FIG7_FOLLOWER_TERMS = [
    [1, 1, 1, 4, 4, 5, 5, 6, 6],
    [1, 1, 1, 4],
    [1, 1, 1, 4, 4, 5, 5, 6, 6, 6, 6],
    [1, 1, 1, 4, 4, 5, 5, 6, 6, 6, 7, 7],
    [1, 1, 1, 4, 4, 4, 4],
    [1, 1, 1, 2, 2, 2, 3, 3, 3, 3, 3],
]


def _load_log(cl, m, terms, term, commit=0):
    """set_node analog of newTestRaft(storage.Append(ents)) + loadState
    (raft_paper_test.go:749-756): entry data = 100*idx + entry term so a
    kept-but-should-be-overwritten entry is detectable."""
    L = cl.spec.L
    lt = np.zeros(L, np.int32)
    ld = np.zeros(L, np.int32)
    for i, t in enumerate(terms, start=1):
        lt[(i - 1) % L] = t
        ld[(i - 1) % L] = 100 * i + t
    cl.set_node(m, term=term, commit=commit, last_index=len(terms),
                log_term=lt, log_data=ld)


@pytest.mark.parametrize("case", range(len(FIG7_FOLLOWER_TERMS)))
def test_leader_sync_follower_log(case):
    """TestLeaderSyncFollowerLog (raft_paper_test.go:695-768, §5.3 fig.7):
    a new leader brings each of the six divergent follower logs of figure 7
    into consistency with its own. Node 2 plays the nopStepper: isolated,
    with its decisive vote injected by hand (raft_paper_test.go:762-764)."""
    cl = Cluster(n_members=3)
    term = 8
    _load_log(cl, 0, LEADER_TERMS, term, commit=len(LEADER_TERMS))
    _load_log(cl, 1, FIG7_FOLLOWER_TERMS[case], term - 1)
    cl.isolate(2)  # nopStepper: receives nothing, says nothing
    cl.campaign(0)
    cl.step()  # candidate at term 9, MsgVotes out
    cl.inject(to=0, frm=2, type=MSG_VOTE_RESP, term=term + 1, reject=False)
    cl.stabilize()
    assert cl.get("role", 0) == ROLE_LEADER
    cl.propose(0, 999)
    cl.stabilize()
    lead_log = cl.log_entries(0)
    # leader log = original 10 entries + empty entry at term 9 + proposal
    assert lead_log[: len(LEADER_TERMS)] == [
        (t, 100 * i + t) for i, t in enumerate(LEADER_TERMS, start=1)
    ]
    assert [t for t, _ in lead_log[len(LEADER_TERMS):]] == [9, 9]
    assert cl.log_entries(1) == lead_log, f"fig.7 case {case}"
    assert cl.get("commit", 1) == cl.get("commit", 0) == len(lead_log)


def test_batched_divergence():
    """Clusters in one batch evolve independently under different inputs."""
    cl = Cluster(n_members=3, C=3)
    cl.campaign(0, c=0)
    cl.campaign(1, c=1)
    cl.stabilize()
    cl.propose(0, 100, c=0)
    cl.stabilize()
    assert cl.leader(0) == 0 and cl.leader(1) == 1 and cl.leader(2) == NONE_ID
    assert cl.commits(0).tolist() == [2, 2, 2]
    assert cl.commits(1).tolist() == [1, 1, 1]
    assert cl.commits(2).tolist() == [0, 0, 0]
