"""The v2 REST façade — /v2/keys, /v2/members, /v2/stats.

Re-design of ``server/etcdserver/api/v2http`` (client.go keysHandler +
parseKeyRequest:346-527, membersHandler, statsHandler) for this
framework's gateway: requests arrive as (method, path, form) triples —
from the JSON/query HTTP server or in-process from clientv2 — and are
parsed with the reference's exact validation ladder and error codes,
then routed through :class:`EtcdCluster`'s consensus front (writes and
quorum reads) or served from the applied tree (plain reads).

Watch (GET ?wait=true) follows this gateway's long-poll convention (see
server/v3rpc.py's watch): if the event is already in history it returns
immediately; otherwise the watcher parks in a registry and the client
polls ``watch_poll`` — the blocking-HTTP analog collapsed to polling,
like the v3 façade's JSON long-poll stands in for gRPC streams.
"""
from __future__ import annotations

from typing import Any

from etcd_tpu.models.changer import ConfChangeError
from etcd_tpu.server.kvserver import EtcdCluster, ServerError
from etcd_tpu.server.v2store import (
    EcodeIndexNaN,
    EcodeInvalidField,
    EcodePrevValueRequired,
    EcodeRaftInternal,
    EcodeRefreshTTLRequired,
    EcodeRefreshValue,
    EcodeTTLNaN,
    Event,
    V2Error,
)

KEYS_PREFIX = "/v2/keys"


def _get_bool(form: dict, name: str) -> bool:
    """getBool (v2http/http.go): absent = false, 'true'/'false' only."""
    v = form.get(name)
    if v is None:
        return False
    if isinstance(v, bool):
        return v
    if v == "true":
        return True
    if v == "false":
        return False
    raise V2Error(EcodeInvalidField, f'invalid value for "{name}"')


def _get_uint(form: dict, name: str, code: int) -> int:
    v = form.get(name)
    if v is None or v == "":
        return 0
    try:
        i = int(v)
        if i < 0:
            raise ValueError
        return i
    except (TypeError, ValueError):
        raise V2Error(code, f'invalid value for "{name}"') from None


def parse_key_request(method: str, form: dict) -> dict:
    """parseKeyRequest (v2http/client.go:346-527): the validation ladder,
    same codes, same order. Returns the RequestV2-shaped dict."""
    prev_index = _get_uint(form, "prevIndex", EcodeIndexNaN)
    wait_index = _get_uint(form, "waitIndex", EcodeIndexNaN)
    recursive = _get_bool(form, "recursive")
    sorted_ = _get_bool(form, "sorted")
    wait = _get_bool(form, "wait")
    dir_ = _get_bool(form, "dir")
    quorum = _get_bool(form, "quorum")
    stream = _get_bool(form, "stream")
    if wait and method != "GET":
        raise V2Error(EcodeInvalidField,
                      '"wait" can only be used with GET requests')
    prev_value = form.get("prevValue", "")
    if "prevValue" in form and prev_value == "":
        raise V2Error(EcodePrevValueRequired,
                      '"prevValue" cannot be empty')
    no_value_on_success = _get_bool(form, "noValueOnSuccess")
    ttl = None
    if form.get("ttl") not in (None, ""):
        ttl = _get_uint(form, "ttl", EcodeTTLNaN)
    prev_exist = None
    if "prevExist" in form:
        prev_exist = _get_bool(form, "prevExist")
    refresh = None
    if "refresh" in form:
        refresh = _get_bool(form, "refresh")
        if refresh:
            if form.get("value"):
                raise V2Error(EcodeRefreshValue,
                              "A value was provided on a refresh")
            if ttl is None:
                raise V2Error(EcodeRefreshTTLRequired, "No TTL value set")
    return {
        "method": method, "value": form.get("value", ""), "dir": dir_,
        "prev_value": prev_value, "prev_index": prev_index,
        "prev_exist": prev_exist, "wait": wait, "wait_index": wait_index,
        "recursive": recursive, "sorted": sorted_, "quorum": quorum,
        "stream": stream, "refresh": bool(refresh), "ttl": ttl,
        "no_value_on_success": no_value_on_success,
    }


class V2Api:
    """keysHandler + membersHandler + statsHandler over EtcdCluster."""

    def __init__(self, ec: EtcdCluster):
        self.ec = ec
        self._watches: dict[int, Any] = {}
        self._next_watch = 1

    # ------------------------------------------------------------- keys
    def keys(self, method: str, key: str,
             form: dict | None = None) -> tuple[int, dict, dict]:
        """One /v2/keys request. Returns (status, body, headers)."""
        form = form or {}
        try:
            r = parse_key_request(method, form)
            if method == "GET":
                return self._get(key, r)
            if method in ("PUT", "POST", "DELETE"):
                ev = self.ec.v2_request(
                    method, key, val=r["value"], dir=r["dir"],
                    prev_value=r["prev_value"],
                    prev_index=r["prev_index"],
                    prev_exist=r["prev_exist"],
                    recursive=r["recursive"], sorted_=r["sorted"],
                    refresh=r["refresh"], ttl=r["ttl"])
                return self._key_event(ev, r)
            raise V2Error(EcodeInvalidField, f"bad method {method}")
        except V2Error as e:
            return e.status_code(), e.to_json(), self._headers()
        except ServerError as e:
            err = V2Error(EcodeRaftInternal, str(e),
                          self._store().current_index)
            return err.status_code(), err.to_json(), self._headers()

    def _store(self):
        return self.ec.members[self.ec.ensure_leader()].v2store

    def _headers(self) -> dict:
        st = self._store()
        return {"X-Etcd-Index": st.current_index}

    def _key_event(self, ev: Event, r: dict) -> tuple[int, dict, dict]:
        # writeKeyEvent: 201 on create, else 200; noValueOnSuccess trims
        status = 201 if ev.is_created() else 200
        body = ev.to_json()
        if r.get("no_value_on_success"):
            body = dict(body)
            node = dict(body["node"])
            node.pop("value", None)
            node.pop("nodes", None)
            body["node"] = node
            body.pop("prevNode", None)
        return status, body, self._headers()

    def _get(self, key: str, r: dict) -> tuple[int, dict, dict]:
        if r["wait"]:
            return self._watch(key, r)
        if r["quorum"]:
            ev = self.ec.v2_request("QGET", key, recursive=r["recursive"],
                                    sorted_=r["sorted"])
        else:
            ev = self.ec.v2_get(key, r["recursive"], r["sorted"])
        return 200, ev.to_json(), self._headers()

    def _watch(self, key: str, r: dict) -> tuple[int, dict, dict]:
        w = self.ec.v2_watch(key, recursive=r["recursive"],
                             stream=r["stream"],
                             since_index=r["wait_index"])
        ev = w.poll()
        if ev is not None and not r["stream"]:
            w.remove()
            return 200, ev.to_json(), self._headers()
        wid = self._next_watch
        self._next_watch += 1
        self._watches[wid] = w
        out: dict[str, Any] = {"watch_id": wid}
        if ev is not None:  # stream watcher with a ready history event
            out["event"] = ev.to_json()
        return 200, out, self._headers()

    def watch_poll(self, watch_id: int) -> tuple[int, dict, dict]:
        w = self._watches.get(watch_id)
        if w is None:
            return 404, {"error": "unknown watch"}, self._headers()
        ev = w.poll()
        if ev is None:
            return 200, {}, self._headers()
        if not w.stream:
            w.remove()
            del self._watches[watch_id]
        return 200, {"event": ev.to_json()}, self._headers()

    def watch_cancel(self, watch_id: int) -> None:
        w = self._watches.pop(watch_id, None)
        if w is not None:
            w.remove()

    # ---------------------------------------------------------- members
    def members(self, method: str, suffix: str = "",
                form: dict | None = None) -> tuple[int, dict, dict]:
        form = form or {}
        try:
            if method == "GET":
                cfg = self.ec.member_config()
                return 200, {"members": [
                    {"id": str(i), "name": f"member{i}",
                     "isLearner": i in cfg.learners}
                    for i in sorted(cfg.progress)
                ]}, self._headers()
            if method == "POST":
                mid = int(form["id"])
                self.ec.member_add(mid,
                                   learner=bool(form.get("isLearner")))
                return 201, {"id": str(mid)}, self._headers()
            if method == "DELETE":
                self.ec.member_remove(int(suffix.strip("/")))
                return 204, {}, self._headers()
            return 405, {"error": "method not allowed"}, self._headers()
        except (ServerError, ConfChangeError, ValueError, KeyError) as e:
            return 500, {"message": str(e)}, self._headers()

    # ------------------------------------------------------------ stats
    def stats(self, which: str) -> tuple[int, dict, dict]:
        if which == "store":
            return 200, self.ec.v2_stats(), self._headers()
        if which == "self":
            lead = self.ec.ensure_leader()
            return 200, {"id": str(lead), "state": "StateLeader"}, \
                self._headers()
        if which == "leader":
            lead = self.ec.ensure_leader()
            return 200, {"leader": str(lead)}, self._headers()
        return 404, {"error": f"unknown stats {which}"}, self._headers()
