"""CLI shell: the etcdmain analog (server/etcdmain/main.go:25,
etcd.go:52) — parse flags into an embed.Config, start the server, serve
until interrupted.

Usage:
    python -m etcd_tpu.etcdmain --listen-client-port 2379 \
        --data-dir /tmp/etcd-tpu --cluster-size 3
"""
from __future__ import annotations

import argparse
import signal
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="etcd-tpu",
        description="TPU-native batched etcd: serve the v3 JSON/HTTP API "
        "over one simulated multi-member cluster",
    )
    p.add_argument("--name", default="default")
    p.add_argument("--data-dir", default=None)
    p.add_argument("--listen-client-host", default="127.0.0.1")
    p.add_argument("--listen-client-port", type=int, default=2379)
    p.add_argument("--cluster-size", type=int, default=3)
    p.add_argument("--heartbeat-interval", type=int, default=100,
                   metavar="MS", dest="tick_ms")
    p.add_argument("--election-timeout", type=int, default=1000,
                   metavar="MS")
    p.add_argument("--quota-backend-bytes", type=int, default=0)
    p.add_argument("--auto-compaction-mode", default="off",
                   choices=("off", "periodic", "revision"))
    p.add_argument("--auto-compaction-retention", type=int, default=0)
    p.add_argument("--pre-vote", action=argparse.BooleanOptionalAction,
                   default=True)
    return p


def main(argv=None) -> int:
    # honor an explicit JAX_PLATFORMS request (this environment's
    # sitecustomize re-pins the accelerator platform at interpreter
    # start, so the env var alone is not enough) and reuse the repo's
    # persistent compile cache for fast process starts
    import os

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    cache = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
    if os.path.isdir(cache):
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)

    from etcd_tpu.embed import Config, start_etcd

    args = build_parser().parse_args(argv)
    cfg = Config(
        name=args.name,
        data_dir=args.data_dir,
        listen_client_host=args.listen_client_host,
        listen_client_port=args.listen_client_port,
        cluster_size=args.cluster_size,
        tick_ms=args.tick_ms,
        election_ticks=max(args.election_timeout // max(args.tick_ms, 1), 2),
        quota_backend_bytes=args.quota_backend_bytes,
        auto_compaction_mode=args.auto_compaction_mode,
        auto_compaction_retention=args.auto_compaction_retention,
        pre_vote=args.pre_vote,
    )
    etcd = start_etcd(cfg)
    print(f"etcd-tpu '{cfg.name}' serving {etcd.client_url} "
          f"({cfg.cluster_size} members)", file=sys.stderr)
    try:
        # race-free: sigwait atomically blocks for either signal
        signal.sigwait({signal.SIGINT, signal.SIGTERM})
    finally:
        etcd.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
