"""InteractionEnv: the rafttest data-driven command language.

Implements the reference's interaction-testing harness
(raft/rafttest/interaction_env.go:33-49, interaction_env_handler.go:29-146)
over :class:`etcd_tpu.models.rawnode.RawNode` lanes: ``add-nodes``,
``campaign``, ``propose``, ``propose-conf-change`` (v1/v2 + transitions),
``deliver-msgs`` (with drops), ``process-ready``, ``stabilize``,
``compact``, ``raft-log``, ``status``, ``tick-heartbeat`` and
``log-level`` — so the reference's golden scenarios
(raft/testdata/*.txt) replay against the TPU engine.

Output mirrors the reference's Describe* formats (raft/util.go:64-210)
and the load-bearing logger lines (role transitions, config switches,
snapshot restores) so goldens can be compared semantically: structural
lines byte-for-byte, logger lines through a curated-event normalizer
(see tests/test_datadriven_interaction.py).

Convention: device member ids are 0-based; all rendered output adds 1, so
NONE_ID (-1) prints as 0 — exactly the reference's "None = 0" convention.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from etcd_tpu.models import confchange as ccmod
from etcd_tpu.models.rawnode import (
    PR_NAMES,
    ErrStepLocalMsg,
    ErrStepPeerNotFound,
    HostMsg,
    RawNode,
    Ready,
    ROLE_NAMES,
)
from etcd_tpu.storage.raftstorage import (
    ConfState,
    Entry,
    MemoryStorage,
    PayloadTable,
    Snapshot,
    SnapshotMeta,
)
from etcd_tpu.types import (
    CC_ADD_LEARNER,
    CC_ADD_NODE,
    CC_REMOVE_NODE,
    CC_UPDATE_NODE,
    ENTRY_CONF_CHANGE,
    ENTRY_NORMAL,
    MSG_APP,
    MSG_APP_RESP,
    MSG_HEARTBEAT,
    MSG_HEARTBEAT_RESP,
    MSG_HUP,
    MSG_PRE_VOTE,
    MSG_PRE_VOTE_RESP,
    MSG_PROP,
    MSG_READ_INDEX,
    MSG_READ_INDEX_RESP,
    MSG_SNAP,
    MSG_SNAP_STATUS,
    MSG_TIMEOUT_NOW,
    MSG_TRANSFER_LEADER,
    MSG_UNREACHABLE,
    MSG_VOTE,
    MSG_VOTE_RESP,
    PR_REPLICATE,
    PR_SNAPSHOT,
    ROLE_CANDIDATE,
    ROLE_FOLLOWER,
    ROLE_LEADER,
    ROLE_PRE_CANDIDATE,
    Spec,
)
from etcd_tpu.utils.config import RaftConfig

MSG_NAMES = {
    MSG_APP: "MsgApp", MSG_APP_RESP: "MsgAppResp",
    MSG_VOTE: "MsgVote", MSG_VOTE_RESP: "MsgVoteResp",
    MSG_SNAP: "MsgSnap", MSG_HEARTBEAT: "MsgHeartbeat",
    MSG_HEARTBEAT_RESP: "MsgHeartbeatResp",
    MSG_PRE_VOTE: "MsgPreVote", MSG_PRE_VOTE_RESP: "MsgPreVoteResp",
    MSG_TRANSFER_LEADER: "MsgTransferLeader",
    MSG_TIMEOUT_NOW: "MsgTimeoutNow",
    MSG_READ_INDEX: "MsgReadIndex", MSG_READ_INDEX_RESP: "MsgReadIndexResp",
    MSG_PROP: "MsgProp", MSG_UNREACHABLE: "MsgUnreachable",
    MSG_SNAP_STATUS: "MsgSnapStatus", MSG_HUP: "MsgHup",
}

ROLE_LOG_NAMES = {
    ROLE_FOLLOWER: "follower",
    ROLE_PRE_CANDIDATE: "pre-candidate",
    ROLE_CANDIDATE: "candidate",
    ROLE_LEADER: "leader",
}

LVL_DEBUG, LVL_INFO, LVL_WARN, LVL_ERROR, LVL_FATAL, LVL_NONE = range(6)
LVL_NAMES = ["DEBUG", "INFO", "WARN", "ERROR", "FATAL", "NONE"]


def _ids_str(ids) -> str:
    return "(" + " ".join(str(i + 1) for i in sorted(ids)) + ")"


def conf_str(cs: ConfState) -> str:
    """tracker.Config.String() (tracker/tracker.go:80-93) +
    quorum Joint/MajorityConfig.String()."""
    out = "voters=" + _ids_str(cs.voters)
    if cs.voters_outgoing:
        out += "&&" + _ids_str(cs.voters_outgoing)
    if cs.learners:
        out += " learners=" + _ids_str(cs.learners)
    if cs.learners_next:
        out += " learners_next=" + _ids_str(cs.learners_next)
    if cs.auto_leave:
        out += " autoleave"
    return out


def conf_state_brackets(cs: ConfState) -> str:
    """DescribeConfState (raft/util.go:78-83)."""
    sq = lambda ids: "[" + " ".join(str(i + 1) for i in sorted(ids)) + "]"
    return (
        f"Voters:{sq(cs.voters)} VotersOutgoing:{sq(cs.voters_outgoing)} "
        f"Learners:{sq(cs.learners)} LearnersNext:{sq(cs.learners_next)} "
        f"AutoLeave:{'true' if cs.auto_leave else 'false'}"
    )


def cc_changes_str(word: int) -> str:
    """ConfChangesToString (raftpb/confchange.go:149-168) for our packed
    conf-change word (models/confchange.py layout)."""
    if ccmod.is_leave_joint(word):
        return ""
    names = {CC_ADD_NODE: "v", CC_REMOVE_NODE: "r", CC_UPDATE_NODE: "u",
             CC_ADD_LEARNER: "l"}
    parts = []
    if word & (1 << 16):
        parts.append(f"{names[word & 7]}{((word >> 3) & 31) + 1}")
    if word & (1 << 17):
        parts.append(f"{names[(word >> 8) & 7]}{((word >> 11) & 31) + 1}")
    return " ".join(parts)


@dataclasses.dataclass
class _StateSnap:
    term: int
    role: int
    lead: int
    vote: int
    snap_index: int
    conf: tuple
    commit: int
    applied: int
    last_index: int
    stored_last: int  # storage.LastIndex(): unstable.offset - 1


# Go value-rendering of ConfChangeV2 (raftpb confchange String forms), used
# by the leader's "ignoring conf change" refusal line (raft.go:1034-1071).
_CC_GO_NAMES = {
    CC_ADD_NODE: "ConfChangeAddNode",
    CC_REMOVE_NODE: "ConfChangeRemoveNode",
    CC_UPDATE_NODE: "ConfChangeUpdateNode",
    CC_ADD_LEARNER: "ConfChangeAddLearnerNode",
}
_TRANSITION_GO = {
    "auto": "ConfChangeTransitionAuto",
    "implicit": "ConfChangeTransitionJointImplicit",
    "explicit": "ConfChangeTransitionJointExplicit",
}


def cc_go_str(changes, transition: str) -> str:
    chs = " ".join(
        "{%s %d}" % (_CC_GO_NAMES[t], nid + 1) for t, nid in changes
    )
    return "{%s [%s] []}" % (_TRANSITION_GO[transition], chs)


class InteractionEnv:
    """Driver state: nodes + in-flight message pool + output buffer
    (raft/rafttest/interaction_env.go:33-49)."""

    def __init__(self, spec: Spec | None = None, cfg: RaftConfig | None = None):
        # defaultRaftConfig (interaction_env.go:64-74): ElectionTick=3,
        # HeartbeatTick=1, no limits — E/W sized so single messages carry
        # whole logs like the reference's MaxUint64 MaxSizePerMsg, and L
        # large enough that the engine's ring-pressure auto-compaction
        # (apply_round's occ > L - 2E trigger) never fires mid-scenario:
        # the reference only compacts on the explicit `compact` command.
        self.spec = spec or Spec(M=8, L=64, E=16, K=8, W=8, R=4, A=8)
        self.cfg = cfg or RaftConfig(
            election_tick=3, heartbeat_tick=1, max_inflight=8
        )
        self.nodes: list[RawNode] = []
        self.storages: list[MemoryStorage] = []
        self.history: list[list[Snapshot]] = []
        self.messages: list[HostMsg] = []
        self.payloads = PayloadTable()
        self.v1_words: set[int] = set()
        # per-node vote tally for the current campaign: (granted, rejected)
        # voter-id sets (the poll() bookkeeping, raft.go:837-845)
        self._votes: dict[int, tuple[set, set]] = {}
        self.lvl = LVL_DEBUG
        self._lines: list[str] = []
        self._indent = 0

    # -- output --------------------------------------------------------------
    def p(self, line: str) -> None:
        for sub in line.split("\n"):
            self._lines.append("  " * self._indent + sub)

    def log(self, lvl: int, line: str) -> None:
        if lvl >= self.lvl:
            self.p(f"{LVL_NAMES[lvl]} {line}")

    # -- id rendering --------------------------------------------------------
    @staticmethod
    def r(i) -> int:
        return int(i) + 1

    # -- describe helpers (raft/util.go) -------------------------------------
    def entry_str(self, e: Entry) -> str:
        if e.type == ENTRY_NORMAL:
            name = "EntryNormal"
            formatted = '"' + self.payloads.lookup(e.data).decode() + '"'
        else:
            name = (
                "EntryConfChange" if e.data in self.v1_words
                else "EntryConfChangeV2"
            )
            formatted = cc_changes_str(e.data)
        sep = " " if formatted else ""
        return f"{e.term}/{e.index} {name}{sep}{formatted}"

    def msg_str(self, m: HostMsg) -> str:
        out = (
            f"{self.r(m.frm)}->{self.r(m.to)} {MSG_NAMES[m.type]} "
            f"Term:{m.term} Log:{m.log_term}/{m.index}"
        )
        if m.reject:
            out += f" Rejected (Hint: {m.reject_hint})"
        if m.commit != 0:
            out += f" Commit:{m.commit}"
        if m.entries:
            out += " Entries:[" + ", ".join(
                self.entry_str(e) for e in m.entries
            ) + "]"
        if m.snapshot is not None and not m.snapshot.is_empty():
            meta = m.snapshot.meta
            out += (
                f" Snapshot: Index:{meta.index} Term:{meta.term} "
                f"ConfState:{conf_state_brackets(meta.conf_state)}"
            )
        return out

    def hard_state_str(self, hs) -> str:
        out = f"Term:{hs.term}"
        if hs.vote != -1:
            out += f" Vote:{self.r(hs.vote)}"
        return out + f" Commit:{hs.commit}"

    def ready_str(self, rd: Ready) -> str:
        parts = []
        if rd.soft_state is not None:
            parts.append(
                f"Lead:{self.r(rd.soft_state.lead)} "
                f"State:{ROLE_NAMES[rd.soft_state.role]}"
            )
        if rd.hard_state is not None:
            parts.append("HardState " + self.hard_state_str(rd.hard_state))
        if rd.read_states:
            rs = " ".join(
                "{" + f"{s.index} {s.request_ctx}" + "}" for s in rd.read_states
            )
            parts.append(f"ReadStates [{rs}]")
        if rd.entries:
            parts.append("Entries:")
            parts.extend(self.entry_str(e) for e in rd.entries)
        if rd.snapshot is not None and not rd.snapshot.is_empty():
            meta = rd.snapshot.meta
            parts.append(
                f"Snapshot Index:{meta.index} Term:{meta.term} "
                f"ConfState:{conf_state_brackets(meta.conf_state)}"
            )
        if rd.committed_entries:
            parts.append("CommittedEntries:")
            parts.extend(self.entry_str(e) for e in rd.committed_entries)
        if rd.messages:
            parts.append("Messages:")
            parts.extend(self.msg_str(m) for m in rd.messages)
        if not parts:
            return "<empty Ready>"
        ms = "true" if rd.must_sync else "false"
        return f"Ready MustSync={ms}:\n" + "\n".join(parts)

    # -- state-diff logger lines --------------------------------------------
    def _snap_state(self, idx: int) -> _StateSnap:
        rn = self.nodes[idx]
        n = rn.n
        return _StateSnap(
            term=int(n.term), role=int(n.role), lead=int(n.lead),
            vote=int(n.vote), snap_index=int(n.snap_index),
            conf=rn._conf_tuple(),
            commit=int(n.commit), applied=int(n.applied),
            last_index=int(n.last_index),
            stored_last=self.storages[idx].last_index(),
        )

    def _last_log(self, idx: int) -> tuple[int, int]:
        """(lastTerm, lastIndex) of a node's log."""
        n = self.nodes[idx].n
        li = int(n.last_index)
        if li == int(n.snap_index):
            return int(n.snap_term), li
        return int(n.log_term[(li - 1) % self.spec.L]), li

    def _term_at(self, idx: int, i: int) -> int:
        """zeroTermOnOutOfBounds(term(i)) (raft log.go)."""
        n = self.nodes[idx].n
        if i == int(n.snap_index):
            return int(n.snap_term)
        if int(n.snap_index) < i <= int(n.last_index):
            return int(n.log_term[(i - 1) % self.spec.L])
        return 0

    def _progress_of(self, idx: int, pid: int):
        return self.nodes[idx].status().progress.get(pid)

    def _emit_transitions(self, idx: int, before: _StateSnap,
                          trigger: HostMsg | None = None) -> None:
        rn = self.nodes[idx]
        n = rn.n
        term, role = int(n.term), int(n.role)
        rid = self.r(idx)
        if (
            trigger is not None
            and trigger.term > before.term
            and term > before.term
        ):
            self.log(
                LVL_INFO,
                f"{rid} [term: {before.term}] received a "
                f"{MSG_NAMES[trigger.type]} message with higher term from "
                f"{self.r(trigger.frm)} [term: {trigger.term}]",
            )
        restored = int(n.snap_index) > before.snap_index and (
            trigger is not None and trigger.type == MSG_SNAP
        )
        if restored:
            # raftLog.restore preamble (raft/log.go:86-90): unstable.offset
            # is one past the last persisted entry; everything this harness
            # appends is persisted at the next Ready, so offset derives from
            # the storage's last index at delivery time.
            self.log(
                LVL_INFO,
                f"log [committed={before.commit}, applied={before.applied}, "
                f"unstable.offset={before.stored_last + 1}, "
                f"len(unstable.Entries)="
                f"{before.last_index - before.stored_last}] starts to "
                f"restore snapshot [index: {int(n.snap_index)}, "
                f"term: {int(n.snap_term)}]",
            )
        if restored and rn._conf_tuple() != before.conf:
            self.log(
                LVL_INFO,
                f"{rid} switched to configuration {conf_str(rn.conf_state())}",
            )
        if role != before.role or term != before.term:
            self.log(
                LVL_INFO,
                f"{rid} became {ROLE_LOG_NAMES[role]} at term {term}",
            )
        if restored:
            si, st = int(n.snap_index), int(n.snap_term)
            c = int(n.commit)
            self.log(
                LVL_INFO,
                f"{rid} [commit: {c}, lastindex: {int(n.last_index)}, "
                f"lastterm: {st}] restored snapshot "
                f"[index: {si}, term: {st}]",
            )
            self.log(
                LVL_INFO,
                f"{rid} [commit: {c}] restored snapshot "
                f"[index: {si}, term: {st}]",
            )

    # -- commands ------------------------------------------------------------
    def add_nodes(self, n: int, voters=(), learners=(), index=0, content=b""):
        """interaction_env_handler_add_nodes.go:54-131."""
        bootstrap = bool(voters or learners or index)
        for _ in range(n):
            idx = len(self.nodes)
            storage = MemoryStorage()
            cs = ConfState(
                voters=tuple(voters), learners=tuple(learners)
            )
            snap = Snapshot(
                meta=SnapshotMeta(
                    index=index, term=1 if bootstrap else 0, conf_state=cs
                ),
                data=(self.payloads.intern(content),) if content else (),
            )
            if bootstrap:
                if index <= 1:
                    raise ValueError(
                        "index must be specified as > 1 due to bootstrap"
                    )
                storage.apply_snapshot(snap)
            rn = RawNode(
                self.cfg, self.spec, storage, idx, applied=index, seed=idx
            )
            self.nodes.append(rn)
            self.storages.append(storage)
            self.history.append([snap])
            rid = self.r(idx)
            self.log(
                LVL_INFO,
                f"{rid} switched to configuration {conf_str(cs)}",
            )
            self.log(LVL_INFO, f"{rid} became follower at term 0")
            peers = ",".join(
                str(self.r(i)) for i in sorted((*voters, *learners))
            )
            n_ = rn.n
            self.log(
                LVL_INFO,
                f"newRaft {rid} [peers: [{peers}], term: 0, commit: "
                f"{int(n_.commit)}, applied: {int(n_.applied)}, lastindex: "
                f"{int(n_.last_index)}, lastterm: "
                f"{int(n_.snap_term) if int(n_.last_index) == int(n_.snap_index) else int(n_.log_term[(int(n_.last_index) - 1) % self.spec.L])}]",
            )

    def campaign(self, idx: int) -> None:
        before = self._snap_state(idx)
        rn = self.nodes[idx]
        msgs0 = len(rn._pending_msgs)
        rid = self.r(idx)
        self.log(
            LVL_INFO,
            f"{rid} is starting a new election at term {before.term}",
        )
        self._votes[idx] = ({idx}, set())
        rn.campaign()
        n = rn.n
        role, term = int(n.role), int(n.term)
        if role == ROLE_LEADER and before.role != ROLE_LEADER:
            # singleton fast path: the whole candidate->leader cascade ran
            # inside one step; reconstruct the intermediate transitions the
            # reference logs one call at a time (campaign, raft.go:785-835)
            self.log(LVL_INFO, f"{rid} became candidate at term {term}")
            self.log(
                LVL_INFO,
                f"{rid} received MsgVoteResp from {rid} at term {term}",
            )
            self.log(LVL_INFO, f"{rid} became leader at term {term}")
        else:
            self._emit_transitions(idx, before)
            self._emit_campaign_lines(idx, before, msgs0)

    def _emit_campaign_lines(self, idx, before, msgs0) -> None:
        rn = self.nodes[idx]
        n = rn.n
        rid = self.r(idx)
        role = int(n.role)
        if role in (ROLE_CANDIDATE, ROLE_PRE_CANDIDATE, ROLE_LEADER):
            # self vote recorded (poll, raft.go:837-845)
            vt = "MsgPreVoteResp" if role == ROLE_PRE_CANDIDATE else "MsgVoteResp"
            self.log(
                LVL_INFO,
                f"{rid} received {vt} from {rid} at term {int(n.term)}",
            )
        for m in rn._pending_msgs[msgs0:]:
            if m.type in (MSG_VOTE, MSG_PRE_VOTE):
                self.log(
                    LVL_INFO,
                    f"{rid} [logterm: {m.log_term}, index: {m.index}] sent "
                    f"{MSG_NAMES[m.type]} request to {self.r(m.to)} at term "
                    f"{int(n.term)}",
                )

    def propose(self, idx: int, data: bytes | str) -> None:
        word = self.payloads.intern(data)
        if not self.nodes[idx].propose(word):
            self._err = "raft proposal dropped"
            self.p(self._err)

    def propose_conf_change(self, idx: int, changes, v1=False,
                            transition="auto") -> None:
        """interaction_env_handler_propose_conf_change.go; encoding per
        ConfChangeV2.EnterJoint/LeaveJoint semantics
        (raftpb/confchange.go:57-102)."""
        if v1 and (len(changes) > 1 or transition != "auto"):
            self.p(
                "v1 conf change can only have one operation and no transition"
            )
            return
        rn = self.nodes[idx]
        if int(rn.n.role) == ROLE_LEADER:
            # the appendEntry guard (raft.go:1034-1071): the leader demotes
            # a refused conf change to an empty entry and says why
            cs = rn.conf_state()
            joint = bool(cs.voters_outgoing)
            wants_leave = not changes
            pci, applied = int(rn.n.pending_conf_index), int(rn.n.applied)
            reason = None
            if pci > applied:
                reason = (
                    f"possible unapplied conf change at index {pci} "
                    f"(applied to {applied})"
                )
            elif joint and not wants_leave:
                reason = "must transition out of joint config first"
            elif not joint and wants_leave:
                reason = "not in joint state; refusing empty conf change"
            if reason:
                self.log(
                    LVL_INFO,
                    f"{self.r(idx)} ignoring conf change "
                    f"{cc_go_str(changes, transition)} at config "
                    f"{conf_str(cs)}: {reason}",
                )
        if not changes and transition == "auto":
            word = ccmod.encode_leave_joint()
        else:
            enter = transition != "auto" or len(changes) > 1
            auto_leave = transition in ("auto", "implicit")
            # the packed word carries at most 2 changes; longer batches only
            # appear in scenarios where the leader must refuse them anyway
            # (joint-config guard demotes the entry to an empty normal one,
            # raft.go:1034-1071), so the truncation is never applied
            word = ccmod.encode(
                changes[:2], enter_joint=enter, auto_leave=auto_leave
            )
        if v1:
            self.v1_words.add(word)
        if not self.nodes[idx].propose_conf_change(word):
            self._err = "raft proposal dropped"
            self.p(self._err)

    def deliver_msgs(self, recipients: list[tuple[int, bool]]) -> int:
        """recipients: [(idx, drop)] (interaction_env_handler_deliver_msgs.go)."""
        count = 0
        for idx, drop in recipients:
            mine = [m for m in self.messages if m.to == idx]
            self.messages = [m for m in self.messages if m.to != idx]
            count += len(mine)
            for m in mine:
                if drop:
                    self.p("dropped: " + self.msg_str(m))
                    continue
                self.p(self.msg_str(m))
                self._deliver_one(idx, m)
        return count

    def _deliver_one(self, idx: int, m: HostMsg) -> None:
        if m.type == MSG_SNAP and m.snapshot is not None:
            # the env overrides snapshot *data* from the sender's history
            # (snapOverrideStorage, interaction_env_handler_add_nodes.go:39-58)
            for snap in reversed(self.history[m.frm]):
                if snap.meta.index <= m.snapshot.meta.index:
                    m = dataclasses.replace(
                        m,
                        snapshot=dataclasses.replace(
                            m.snapshot, data=snap.data
                        ),
                    )
                    break
        rn = self.nodes[idx]
        before = self._snap_state(idx)
        msgs0 = len(rn._pending_msgs)
        # pre-step observations for the logger lines only derivable from
        # state the step overwrites
        lead_resp = (
            m.type in (MSG_APP_RESP, MSG_HEARTBEAT_RESP)
            and before.role == ROLE_LEADER
        )
        pre_prog = self._progress_of(idx, m.frm) if lead_resp else None
        pre_terms = (
            np.asarray(rn.n.log_term)
            if m.type == MSG_APP and m.entries else None
        )
        try:
            rn.step(m)
        except (ErrStepLocalMsg, ErrStepPeerNotFound) as e:
            self.p(str(e))
            return
        delta = rn._pending_msgs[msgs0:]
        self._emit_vote_tally(idx, before, m)
        # becomeCandidate/becomePreCandidate reset the poll bookkeeping;
        # a step-triggered candidacy (pre-vote won, MsgTimeoutNow) must
        # reset it here too, after the triggering response was tallied
        role_now = int(rn.n.role)
        stepped_into_candidacy = role_now in (
            ROLE_CANDIDATE, ROLE_PRE_CANDIDATE
        ) and role_now != before.role
        if stepped_into_candidacy:
            self._votes[idx] = ({idx}, set())
        self._emit_transitions(idx, before, trigger=m)
        if stepped_into_candidacy:
            # campaign() ran inside this step (pre-vote won, MsgTimeoutNow):
            # Go logs the self-vote poll and the vote-request sends too
            self._emit_campaign_lines(idx, before, msgs0)
        self._emit_post_step(idx, before, m, delta, pre_prog, pre_terms)

    def _emit_vote_tally(self, idx: int, before: _StateSnap,
                         m: HostMsg) -> None:
        """poll() receipt + tally (raft.go:837-845, stepCandidate) — logged
        before any role transition the response triggers."""
        if (
            m.type not in (MSG_VOTE_RESP, MSG_PRE_VOTE_RESP)
            or before.role not in (ROLE_CANDIDATE, ROLE_PRE_CANDIDATE)
            or m.term < before.term  # stale responses are ignored outright
        ):
            return
        # a response at a higher term dethrones the candidate instead of
        # being polled — EXCEPT a granted pre-vote response, which echoes
        # the candidate's future term (raft.go Step's MsgPreVoteResp carve-
        # out) and is the normal pre-vote grant
        if m.term > before.term and not (
            m.type == MSG_PRE_VOTE_RESP and not m.reject
        ):
            return
        gr, rj = self._votes.setdefault(idx, (set(), set()))
        (rj if m.reject else gr).add(m.frm)
        rid = self.r(idx)
        name = MSG_NAMES[m.type]
        if m.reject:
            self.log(
                LVL_INFO,
                f"{rid} received {name} rejection from {self.r(m.frm)} "
                f"at term {before.term}",
            )
        else:
            self.log(
                LVL_INFO,
                f"{rid} received {name} from {self.r(m.frm)} "
                f"at term {before.term}",
            )
        self.log(
            LVL_INFO,
            f"{rid} has received {len(gr)} {name} votes and "
            f"{len(rj)} vote rejections",
        )

    def _emit_post_step(self, idx: int, before: _StateSnap, m: HostMsg,
                        delta: list[HostMsg], pre_prog,
                        pre_terms) -> None:
        """Logger lines derived from what the step did: vote casting,
        append rejection/conflict, and the leader's probe/snapshot
        bookkeeping (raft.go stepLeader / handleAppendEntries)."""
        rn = self.nodes[idx]
        n = rn.n
        rid = self.r(idx)
        if m.type in (MSG_VOTE, MSG_PRE_VOTE):
            resp = next(
                (p for p in delta
                 if p.type in (MSG_VOTE_RESP, MSG_PRE_VOTE_RESP)), None
            )
            if resp is None:
                return
            lt, li = self._last_log(idx)
            # r.Vote at log time: reset by a real-vote term bump; a
            # pre-vote never changes term or vote, so the recorded vote
            # still shows
            shown = (
                0 if m.term > before.term and m.type == MSG_VOTE
                else self.r(before.vote)
            )
            verb = (
                f"rejected {MSG_NAMES[m.type]} from"
                if resp.reject else f"cast {MSG_NAMES[m.type]} for"
            )
            self.log(
                LVL_INFO,
                f"{rid} [logterm: {lt}, index: {li}, vote: {shown}] {verb} "
                f"{self.r(m.frm)} [logterm: {m.log_term}, "
                f"index: {m.index}] at term {int(n.term)}",
            )
        elif m.type == MSG_APP:
            reject = next(
                (p for p in delta if p.type == MSG_APP_RESP and p.reject),
                None,
            )
            if reject is not None:
                # handleAppendEntries rejection (raft.go:1633-1668); the
                # log is untouched, so the post-step term lookup is the
                # pre-step one
                self.log(
                    LVL_DEBUG,
                    f"{rid} [logterm: {self._term_at(idx, m.index)}, "
                    f"index: {m.index}] rejected MsgApp "
                    f"[logterm: {m.log_term}, index: {m.index}] "
                    f"from {self.r(m.frm)}",
                )
            elif pre_terms is not None:
                # findConflict + truncateAndAppend (raft/log.go:118-151):
                # first overlapping entry whose stored term differs
                for e in m.entries:
                    if e.index > before.last_index:
                        break
                    if e.index <= before.snap_index:
                        continue
                    ext = int(pre_terms[(e.index - 1) % self.spec.L])
                    if ext != e.term:
                        self.log(
                            LVL_INFO,
                            f"found conflict at index {e.index} [existing "
                            f"term: {ext}, conflicting term: {e.term}]",
                        )
                        self.log(
                            LVL_INFO,
                            f"replace the unstable entries from index "
                            f"{e.index}",
                        )
                        break
        elif pre_prog is not None:
            # one post-step Status serves the response lookup and every
            # snapshot the step emitted
            post_progs = rn.status().progress
            post = post_progs.get(m.frm)
            if m.type == MSG_APP_RESP and m.reject:
                self.log(
                    LVL_DEBUG,
                    f"{rid} received MsgAppResp(rejected, hint: (index "
                    f"{m.reject_hint}, term {m.log_term})) from "
                    f"{self.r(m.frm)} for index {m.index}",
                )
                if post is not None and (
                    (post.match, post.next) != (pre_prog.match, pre_prog.next)
                    or post.state != pre_prog.state
                ):
                    # MaybeDecrTo succeeded. The reference prints the
                    # progress between the decrease and the BecomeProbe/
                    # snapshot transition the same step performs: a
                    # replicating peer still shows StateReplicate with
                    # next=match+1 (tracker MaybeDecrTo's replicate arm);
                    # a probing one shows the new next, unchanged by the
                    # later transition.
                    if pre_prog.state == PR_REPLICATE:
                        shown = (
                            f"StateReplicate match={pre_prog.match} "
                            f"next={pre_prog.match + 1}"
                        )
                    else:
                        shown = (
                            f"StateProbe match={post.match} "
                            f"next={post.next}"
                        )
                    self.log(
                        LVL_DEBUG,
                        f"{rid} decreased progress of {self.r(m.frm)} to "
                        f"[{shown}]",
                    )
            elif (
                m.type == MSG_APP_RESP
                and pre_prog.state == PR_SNAPSHOT
                and post is not None
                and post.state != PR_SNAPSHOT
            ):
                nxt = max(pre_prog.next, m.index + 1)
                self.log(
                    LVL_DEBUG,
                    f"{rid} recovered from needing snapshot, resumed "
                    f"sending replication messages to {self.r(m.frm)} "
                    f"[StateSnapshot match={m.index} next={nxt} paused "
                    f"pendingSnap={pre_prog.pending_snapshot}]",
                )
            for pm in delta:
                if pm.type != MSG_SNAP or pm.snapshot is None:
                    continue
                p = post_progs.get(pm.to)
                meta = pm.snapshot.meta
                self.log(
                    LVL_DEBUG,
                    f"{rid} [firstindex: {int(n.snap_index) + 1}, "
                    f"commit: {int(n.commit)}] sent snapshot"
                    f"[index: {meta.index}, term: {meta.term}] to "
                    f"{self.r(pm.to)} [StateProbe match={p.match} "
                    f"next={p.next}]",
                )
                self.log(
                    LVL_DEBUG,
                    f"{rid} paused sending replication messages to "
                    f"{self.r(pm.to)} [{p}]",
                )

    def process_ready(self, idx: int) -> None:
        """interaction_env_handler_process_ready.go:40-102."""
        rn, storage = self.nodes[idx], self.storages[idx]
        rd = rn.ready()
        self.p(self.ready_str(rd))
        if rd.hard_state is not None:
            storage.set_hard_state(rd.hard_state)
        if rd.entries:
            storage.append(rd.entries)
        if rd.snapshot is not None and not rd.snapshot.is_empty():
            storage.apply_snapshot(rd.snapshot)
        self.messages.extend(rd.messages)
        rn.advance(rd)
        for cs in rn.last_conf_states:
            self.log(
                LVL_INFO,
                f"{self.r(idx)} switched to configuration {conf_str(cs)}",
            )
            if (
                cs.voters_outgoing and cs.auto_leave
                and int(rn.n.role) == ROLE_LEADER
            ):
                # the leader schedules the empty leave-joint entry the
                # moment it applies an auto-leave joint config
                # (raft.go:668-692)
                self.log(
                    LVL_INFO,
                    "initiating automatic transition out of joint "
                    f"configuration {conf_str(cs)}",
                )
        # the "appender state machine" history (process_ready.go:64-90)
        hist = self.history[idx]
        for e in rd.committed_entries:
            last = hist[-1]
            data = last.data
            if e.type == ENTRY_NORMAL and e.data:
                data = data + (e.data,)
            hist.append(
                Snapshot(
                    meta=SnapshotMeta(
                        index=e.index, term=e.term,
                        conf_state=rn.conf_state(),
                        app_hash=int(rn.n.applied_hash),
                    ),
                    data=data,
                )
            )

    def stabilize(self, idxs: list[int] | None = None) -> None:
        """Fixpoint loop (interaction_env_handler_stabilize.go:152-185)."""
        sel = idxs if idxs else list(range(len(self.nodes)))
        while True:
            done = True
            for idx in sel:
                if self.nodes[idx].has_ready():
                    done = False
                    self.p(f"> {self.r(idx)} handling Ready")
                    self._indent += 1
                    self.process_ready(idx)
                    self._indent -= 1
            for idx in sel:
                if any(m.to == idx for m in self.messages):
                    done = False
                    self.p(f"> {self.r(idx)} receiving messages")
                    self._indent += 1
                    self.deliver_msgs([(idx, False)])
                    self._indent -= 1
            if done:
                return

    def compact(self, idx: int, compact_index: int) -> None:
        self.storages[idx].compact(compact_index)
        self.nodes[idx].compact_to(compact_index)
        self.raft_log(idx)

    def raft_log(self, idx: int) -> None:
        storage = self.storages[idx]
        fi, li = storage.first_index(), storage.last_index()
        if li < fi:
            self.p(f"log is empty: first index={fi}, last index={li}")
            return
        for e in storage.entries(fi, li + 1):
            self.p(self.entry_str(e))

    def status(self, idx: int) -> None:
        st = self.nodes[idx].status()
        for pid in sorted(st.progress):
            self.p(f"{self.r(pid)}: {st.progress[pid]}")

    def tick_heartbeat(self, idx: int) -> None:
        self.nodes[idx].tick()

    # -- dispatcher ----------------------------------------------------------
    def handle(self, case) -> str:
        """Execute one datadriven Case; returns the output block
        (interaction_env_handler.go:29-146)."""
        self._lines = []
        self._err = None
        try:
            self._dispatch(case)
        except Exception as e:  # errors go to the output buffer
            self._err = f"{type(e).__name__}: {e}"
            self.p(self._err)
        if not self._lines:
            return "ok"
        if self.lvl == LVL_NONE:
            return self._err if self._err else "ok (quiet)"
        return "\n".join(self._lines)

    def _dispatch(self, case) -> None:
        cmd, args, inp = case.cmd, case.args, case.input
        pos = args.get("_pos", [])
        if cmd == "log-level":
            name = pos[0]
            self.lvl = LVL_NAMES.index(name.upper())
            return
        if cmd == "add-nodes":
            ids = lambda key: tuple(int(v) - 1 for v in args.get(key, []))
            self.add_nodes(
                int(pos[0]),
                voters=ids("voters"),
                learners=ids("learners"),
                index=int(args.get("index", [0])[0]),
                content=args.get("content", [""])[0],
            )
            return
        if cmd == "campaign":
            self.campaign(int(pos[0]) - 1)
            return
        if cmd == "propose":
            self.propose(int(pos[0]) - 1, pos[1])
            return
        if cmd == "propose-conf-change":
            ops = {"v": CC_ADD_NODE, "l": CC_ADD_LEARNER,
                   "r": CC_REMOVE_NODE, "u": CC_UPDATE_NODE}
            changes = []
            for tok in " ".join(inp).split():
                changes.append((ops[tok[0]], int(tok[1:]) - 1))
            self.propose_conf_change(
                int(pos[0]) - 1,
                changes,
                v1=args.get("v1", ["false"])[0] == "true",
                transition=args.get("transition", ["auto"])[0],
            )
            return
        if cmd == "deliver-msgs":
            rs = [(int(v) - 1, False) for v in pos]
            rs += [(int(v) - 1, True) for v in args.get("drop", [])]
            if self.deliver_msgs(rs) == 0:
                self.p("no messages")
            return
        if cmd == "process-ready":
            idxs = [int(v) - 1 for v in pos]
            for idx in idxs:
                if len(idxs) > 1:
                    self.p(f"> {self.r(idx)} handling Ready")
                    self._indent += 1
                    self.process_ready(idx)
                    self._indent -= 1
                else:
                    self.process_ready(idx)
            return
        if cmd == "stabilize":
            self.stabilize([int(v) - 1 for v in pos])
            return
        if cmd == "compact":
            self.compact(int(pos[0]) - 1, int(pos[1]))
            return
        if cmd == "raft-log":
            self.raft_log(int(pos[0]) - 1)
            return
        if cmd == "status":
            self.status(int(pos[0]) - 1)
            return
        if cmd == "tick-heartbeat":
            self.tick_heartbeat(int(pos[0]) - 1)
            return
        if cmd == "_breakpoint":
            return
        raise ValueError(f"unknown command {cmd}")
