"""Replay the reference's quorum golden files against the TPU kernels.

Source of truth: raft/quorum/testdata/{majority,joint}_{commit,vote}.txt
driven by raft/quorum/datadriven_test.go. Each case gives configs as voter
id lists and per-voter acked indexes / votes; the last line of the expected
block is the committed index (∞ for the empty config) or the VoteResult.
We map the arbitrary uint64 ids onto dense slots and compare numerically.
"""
import re

import jax.numpy as jnp
import numpy as np
import pytest

from etcd_tpu.harness import datadriven as dd
from etcd_tpu.ops import quorum
from etcd_tpu.types import INT32_MAX, VOTE_LOST, VOTE_PENDING, VOTE_WON

pytestmark = pytest.mark.skipif(
    not dd.reference_available(), reason="reference testdata not mounted"
)


def _cases(fname):
    if not dd.reference_available():
        return []
    return dd.parse_file(dd.testdata("quorum", "testdata", fname))


def _slots(args):
    """Map uint64 ids -> dense slot ids, ordered as (cfg, then new-in-cfgj);
    returns (ids_order, voters_mask, votersj_mask, joint)."""
    ids = [int(v) for v in args.get("cfg", [])]
    joint = "cfgj" in args
    idsj = [int(v) for v in args.get("cfgj", []) if v != "zero"]
    order = list(ids)
    for i in idsj:
        if i not in order:
            order.append(i)
    M = max(len(order), 1)
    slot = {i: s for s, i in enumerate(order)}
    v = np.zeros(M, bool)
    vj = np.zeros(M, bool)
    for i in ids:
        v[slot[i]] = True
    for i in idsj:
        vj[slot[i]] = True
    return order, v, vj, joint


def _expected_tail(case):
    last = case.expected[-1].strip() if case.expected else ""
    return last


@pytest.mark.parametrize("fname", ["majority_commit.txt", "joint_commit.txt"])
def test_committed_index_goldens(fname):
    cases = _cases(fname)
    assert cases, fname
    for case in cases:
        assert case.cmd == "committed", case.line
        order, v, vj, joint = _slots(case.args)
        idx_raw = case.args.get("idx", [])
        acked = np.zeros(len(order) or 1, np.int32)
        for pos, val in enumerate(idx_raw):
            if val != "_":
                acked[pos] = int(val)
        got = quorum.joint_committed_index(
            jnp.asarray(v), jnp.asarray(vj), jnp.asarray(acked)
        ) if joint else quorum.committed_index(jnp.asarray(v), jnp.asarray(acked))
        got = int(got)
        tail = _expected_tail(case)
        if tail.endswith("∞"):
            want = INT32_MAX
        else:
            m = re.search(r"(\d+)\s*$", tail)
            assert m, (case.line, tail)
            want = int(m.group(1))
        assert got == want, f"{fname}:{case.line}: got {got} want {want}"


@pytest.mark.parametrize("fname", ["majority_vote.txt", "joint_vote.txt"])
def test_vote_result_goldens(fname):
    cases = _cases(fname)
    assert cases, fname
    names = {VOTE_WON: "VoteWon", VOTE_LOST: "VoteLost", VOTE_PENDING: "VotePending"}
    for case in cases:
        assert case.cmd == "vote", case.line
        order, v, vj, joint = _slots(case.args)
        votes_raw = case.args.get("votes", [])
        M = len(order) or 1
        responded = np.zeros(M, bool)
        granted = np.zeros(M, bool)
        for pos, val in enumerate(votes_raw):
            if val == "y":
                responded[pos] = granted[pos] = True
            elif val == "n":
                responded[pos] = True
        got = quorum.joint_vote_result(
            jnp.asarray(v), jnp.asarray(vj), jnp.asarray(responded),
            jnp.asarray(granted),
        ) if joint else quorum.vote_result(
            jnp.asarray(v), jnp.asarray(responded), jnp.asarray(granted)
        )
        want = _expected_tail(case)
        assert names[int(got)] == want, f"{fname}:{case.line}"
