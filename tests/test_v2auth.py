"""v2 auth: users/roles/guards over the replicated security subtree
(api/v2auth/auth.go + v2http/client_auth.go)."""
import pytest

from etcd_tpu import clientv2
from etcd_tpu.server.kvserver import EtcdCluster
from etcd_tpu.server.v2auth import (
    AuthError,
    V2AuthStore,
    has_access,
    prefix_match,
    simple_match,
)
from etcd_tpu.server.v2http import V2Api
from etcd_tpu.server.v2store import EcodeUnauthorized


@pytest.fixture()
def ec():
    c = EtcdCluster(n_members=3)
    c.ensure_leader()
    return c


@pytest.fixture()
def auth(ec):
    return V2AuthStore(ec)


# ------------------------------------------------------- pattern match

def test_match_semantics():
    assert simple_match("/foo/*", "/foo/bar")
    assert simple_match("/foo", "/foo")
    assert not simple_match("/foo", "/foo/bar")
    assert prefix_match("/foo*", "/foo")
    assert not prefix_match("/foo/*", "/foo")  # the reference quirk
    assert not prefix_match("/foo", "/foo")


def test_has_access():
    perms = {"kv": {"read": ["/r/*"], "write": ["/w/only"]}}
    assert has_access(perms, "/r/x", write=False)
    assert not has_access(perms, "/r/x", write=True)
    assert has_access(perms, "/w/only", write=True)
    assert not has_access(perms, "/w/other", write=True)


# ------------------------------------------------------------ store

def test_user_role_crud(auth):
    auth.create_user("alice", "pw", ["r1"])
    u = auth.get_user("alice")
    assert u["roles"] == ["r1"]
    with pytest.raises(AuthError, match="already exists"):
        auth.create_user("alice", "pw2")
    auth.update_user("alice", grant=["r2"])
    assert auth.get_user("alice")["roles"] == ["r1", "r2"]
    with pytest.raises(AuthError, match="duplicate role"):
        auth.update_user("alice", grant=["r2"])
    auth.update_user("alice", revoke=["r1"])
    assert auth.get_user("alice")["roles"] == ["r2"]
    assert auth.all_users() == ["alice"]
    auth.delete_user("alice")
    with pytest.raises(AuthError, match="does not exist"):
        auth.get_user("alice")

    auth.create_role("reader", {"kv": {"read": ["/a/*"], "write": []}})
    r = auth.get_role("reader")
    assert r["permissions"]["kv"]["read"] == ["/a/*"]
    auth.update_role("reader",
                     grant={"kv": {"read": ["/b/*"], "write": []}})
    assert auth.get_role("reader")["permissions"]["kv"]["read"] == \
        ["/a/*", "/b/*"]
    with pytest.raises(AuthError, match="duplicate permission"):
        auth.update_role("reader",
                         grant={"kv": {"read": ["/b/*"], "write": []}})
    with pytest.raises(AuthError, match="invalid role name"):
        auth.create_role("root")
    assert "root" in auth.all_roles()


def test_enable_requires_root(auth):
    with pytest.raises(AuthError, match="No root user"):
        auth.enable_auth()
    auth.create_user("root", "rpw")
    auth.enable_auth()
    assert auth.auth_enabled()
    # guest role auto-created with full access
    assert auth.get_role("guest")["permissions"]["kv"]["read"] == ["/*"]
    with pytest.raises(AuthError, match="already enabled"):
        auth.enable_auth()
    with pytest.raises(AuthError, match="cannot delete root"):
        auth.delete_user("root")
    auth.disable_auth()
    assert not auth.auth_enabled()


def test_guard(auth):
    auth.create_user("root", "rpw")
    auth.create_user("bob", "bpw", ["writer"])
    auth.create_role("writer",
                     {"kv": {"read": ["/app/*"], "write": ["/app/*"]}})
    auth.enable_auth()
    # default guest role is full-access: everything still allowed
    auth.check_key_access(None, "/anything", write=True)
    # restrict guests to read-only
    auth.update_role("guest",
                     revoke={"kv": {"read": [], "write": ["/*"]}})
    with pytest.raises(AuthError):
        auth.check_key_access(None, "/app/x", write=True)
    auth.check_key_access(None, "/app/x", write=False)
    # bob can write inside /app, nowhere else
    auth.check_key_access(("bob", "bpw"), "/app/x", write=True)
    with pytest.raises(AuthError):
        auth.check_key_access(("bob", "bpw"), "/other", write=True)
    with pytest.raises(AuthError, match="incorrect password"):
        auth.check_key_access(("bob", "WRONG"), "/app/x", write=True)
    # root bypasses everything; the security subtree stays internal
    auth.check_key_access(("root", "rpw"), "/other", write=True)
    with pytest.raises(AuthError):
        auth.check_key_access(("root", "rpw"), "/_security/users/x",
                              write=False)


def test_guard_replicates(ec, auth):
    """Auth records live in the replicated tree: every member agrees."""
    auth.create_user("root", "rpw")
    auth.enable_auth()
    ec.stabilize()
    saves = [ms.v2store.save() for ms in ec.members]
    assert saves[0] == saves[1] == saves[2]
    assert V2AuthStore(ec).auth_enabled()


# ------------------------------------------------------------- façade

def test_v2api_guard_and_admin(ec):
    api = V2Api(ec)
    root = clientv2.new(api, "root", "rpw")
    anon = clientv2.new(api)
    # before enable: admin open, keys open
    root.auth.add_user("root", "rpw")
    root.auth.add_role("writer",
                       {"kv": {"read": ["/app/*"],
                               "write": ["/app/*"]}})
    root.auth.add_user("bob", "bpw", ["writer"])
    root.auth.enable()
    assert root.auth.enabled()
    # lock guests out of writes
    root.auth.revoke_role("guest",
                          {"kv": {"read": [], "write": ["/*"]}})
    st, body, _ = api.keys("PUT", "/app/x", {"value": "v"})
    assert st == 403 and body["errorCode"] == EcodeUnauthorized
    bob = clientv2.new(api, "bob", "bpw")
    assert bob.keys.set("/app/x", "v").action == "set"
    with pytest.raises(clientv2.Error):
        bob.keys.set("/elsewhere", "v")
    assert anon.keys.get("/app/x").node["value"] == "v"
    # admin requires root now
    st, body, _ = api.auth_admin("GET", "/users", {})
    assert st == 401
    assert bob.auth is not None
    with pytest.raises(clientv2.Error):
        bob.auth.list_users()
    assert root.auth.list_users() == ["bob", "root"]
    assert root.auth.get_user("bob")["roles"] == ["writer"]
    root.auth.disable()
    assert api.keys("PUT", "/free", {"value": "v"})[0] == 201
