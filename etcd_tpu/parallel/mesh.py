"""Sharding the fleet over a device mesh.

The reference scales by running more processes connected over rafthttp
(server/etcdserver/api/rafthttp/) — its NCCL/MPI analog. The TPU-native
equivalent shards the *clusters* axis of the fleet over a
``jax.sharding.Mesh``: every cluster's message exchange is a within-cluster
transpose (member axis stays on-device), so the clusters axis is purely
data-parallel and XLA places one shard per device with zero collectives in
the steady state — the ICI/DCN budget is spent only by the host driver
(proposal feed / applied drain), mirroring rafthttp's "client traffic at the
edge, peer traffic inside" split.

Layout: the fleet is **clusters-minor** — the huge C axis is the LAST axis
of every leaf (state ``[M, ..., C]``, inbox ``[to, from, K, (E,) C]``,
keep-mask ``[from, to, C]``) so TPU (8,128) tiling pads only the tiny
member axes. The mesh therefore shards the *last* axis of every leaf.

Entry points:
  * :func:`build_sharded_round` — jit of the fused round with per-leaf
    ``NamedSharding`` constraints on the trailing clusters axis.
  * :func:`build_shard_map_round` — explicit ``shard_map`` over the clusters
    axis, the form that composes with cross-shard collectives (e.g. global
    invariant checks via ``psum``) and with a second DCN mesh axis.
  * :func:`build_scan_rounds` — on-device lax.scan of many rounds.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from etcd_tpu.models.engine import build_round, empty_inbox, init_fleet
from etcd_tpu.types import Spec
from etcd_tpu.utils.config import RaftConfig

CLUSTER_AXIS = "clusters"
# 2-D mesh axis names (SURVEY §2.3): the clusters axis is sharded over
# BOTH — outer splits ride DCN (slice/host boundaries), inner splits
# ride ICI. Steady-state consensus needs zero collectives either way
# (clusters are independent); only the invariant psum crosses the mesh,
# and it reduces over ICI first, DCN last — exactly the hierarchy the
# hardware wants.
DCN_AXIS = "dcn"
ICI_AXIS = "ici"


def make_fleet_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the clusters axis. On multi-host topologies the same
    axis spans DCN transparently (device order follows jax.devices())."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    import numpy as np

    return Mesh(np.asarray(devices),  # lint: allow(host-sync) -- numpy over the host device list, no array data crosses
                (CLUSTER_AXIS,))


def make_fleet_mesh_2d(dcn: int, ici: int, devices=None) -> Mesh:
    """2-D (DCN x ICI) mesh: `dcn` slices of `ici` devices each. The
    fleet's clusters axis shards over the flattened (dcn, ici) grid —
    device order follows jax.devices(), which enumerates ICI-connected
    devices within a slice contiguously, so the inner axis is the
    fast one. The reference's analog is many etcd processes bridged by
    rafthttp over LAN/WAN; here the WAN tier is DCN between slices."""
    if devices is None:
        devices = jax.devices()
    devices = devices[: dcn * ici]
    if len(devices) < dcn * ici:
        raise ValueError(
            f"2-D mesh needs {dcn * ici} devices, have {len(devices)}")
    import numpy as np

    return Mesh(
        np.asarray(devices).reshape(dcn, ici),  # lint: allow(host-sync) -- numpy over the host device list, no array data crosses
        (DCN_AXIS, ICI_AXIS)
    )


def _mesh_axes(mesh: Mesh) -> tuple:
    """Every mesh axis shards the trailing clusters dim (1-D: clusters;
    2-D: (dcn, ici) flattened — outer=DCN, inner=ICI)."""
    names = tuple(mesh.axis_names)
    return names if len(names) > 1 else names[0]


def _last_axis_p(x, axes=CLUSTER_AXIS) -> P:
    """PartitionSpec sharding the trailing (clusters) axis of one leaf."""
    return P(*([None] * (x.ndim - 1)), axes)


def _leaf_sharding(mesh: Mesh, x) -> NamedSharding:
    return NamedSharding(mesh, _last_axis_p(x, _mesh_axes(mesh)))


def shard_fleet(mesh: Mesh, *trees):
    """Place every leaf of each pytree with its trailing C axis split over
    the mesh. Returns the trees device-put with NamedSharding."""

    def put(x):
        return jax.device_put(x, _leaf_sharding(mesh, x))

    out = tuple(jax.tree.map(put, t) for t in trees)
    return out[0] if len(out) == 1 else out


def _constrain(mesh: Mesh, tree):
    return jax.tree.map(
        lambda x: jax.lax.with_sharding_constraint(x, _leaf_sharding(mesh, x)),
        tree,
    )


def fleet_in_specs(cfg: RaftConfig, spec: Spec, mesh: Mesh | None = None):
    """Per-leaf PartitionSpecs (trailing axis on the mesh) for the 9 round
    args: (state, inbox, prop_len, prop_data, prop_type, ri_ctx, do_hup,
    do_tick, keep_mask). Computed abstractly — no arrays materialised.
    Honors the cfg's storage forms: PackedFleet leaves under packed_state,
    the [B, to, C] compacted wire under compact_wire — every diet leaf
    keeps the trailing clusters axis, so the sharding rule is unchanged."""
    axes = _mesh_axes(mesh) if mesh is not None else CLUSTER_AXIS

    def mk_state():
        st = init_fleet(spec, 2, election_tick=cfg.election_tick)
        if cfg.packed_state:
            from etcd_tpu.models.state import pack_fleet

            st = pack_fleet(spec, st)
        return st

    st = jax.eval_shape(mk_state)
    # the inbox is built EAGERLY (a few KB at C=2): empty_inbox routes
    # through the lru-cached types.empty_msg, and eval_shape would
    # poison that cache with tracer leaves for this (spec, backend) key
    # (see engine.inbox_bytes_per_group)
    ib = empty_inbox(
        spec, 2, wire_int16=cfg.wire_int16,
        compact_bound=cfg.inbox_bound if cfg.compact_wire else 0,
    )
    state_specs = jax.tree.map(lambda x: _last_axis_p(x, axes), st)
    inbox_specs = jax.tree.map(lambda x: _last_axis_p(x, axes), ib)
    v2 = P(None, axes)
    v3 = P(None, None, axes)
    return (state_specs, inbox_specs, v2, v3, v3, v2, v2, v2, v3)


def build_sharded_round(cfg: RaftConfig, spec: Spec, mesh: Mesh,
                        donate: bool = True):
    """Jitted round with all inputs/outputs constrained to the clusters
    sharding. Identical math to engine.build_round; placement only.

    ``donate=True`` (default) donates the fleet carry (state + inbox):
    the per-round dispatch updates the sharded fleet in place instead of
    double-buffering GBs of HBM across it. Callers that re-read a
    pre-round fleet reference (reuse raises a deleted-buffer error) pass
    donate=False — the interactive/debug fallback."""
    round_fn = build_round(cfg, spec)

    def constrained(*args):
        args = tuple(_constrain(mesh, a) for a in args)
        state, inbox = round_fn(*args)
        return _constrain(mesh, state), _constrain(mesh, inbox)

    return jax.jit(constrained, donate_argnums=(0, 1) if donate else ())


def build_shard_map_round(cfg: RaftConfig, spec: Spec, mesh: Mesh,
                          donate: bool = True):
    """shard_map form: each device steps its C/n_devices cluster shard
    locally. Composes with cross-shard collectives (psum of invariant
    violations etc.) and nested member-axis sharding later. Donation as
    in build_sharded_round (donate=False = non-donated fallback)."""
    round_fn = build_round(cfg, spec)
    in_specs = fleet_in_specs(cfg, spec, mesh)

    fn = shard_map(
        round_fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(in_specs[0], in_specs[1]),
        check_rep=False,
    )
    return jax.jit(fn, donate_argnums=(0, 1) if donate else ())


def build_global_invariants(cfg: RaftConfig, spec: Spec, mesh: Mesh):
    """Fleet-wide safety counters over a SHARDED fleet without gathering
    it: every device runs the chaos checker (harness/chaos.py
    check_invariants — pure reductions over its local [M, ..., C/n]
    cluster shard) and ONE scalar psum per counter crosses the mesh.
    This is the cross-shard composition build_shard_map_round exists
    for: per-shard math + a collective only at the invariant boundary,
    so the ICI cost is one scalar per Violations counter (6 since the
    crash tier) per check instead of the fleet."""
    from etcd_tpu.harness.chaos import check_invariants, zero_violations

    axes = _mesh_axes(mesh)
    st = jax.eval_shape(
        lambda: init_fleet(spec, 2, election_tick=cfg.election_tick)
    )
    state_specs = jax.tree.map(lambda x: _last_axis_p(x, axes), st)

    def _reduce(x):
        if isinstance(axes, str):
            return jax.lax.psum(x, axes)
        # genuinely hierarchical on the 2-D mesh: one psum per axis,
        # inner (ICI) first so the cross-slice DCN hop reduces
        # already-combined partials — a single psum over both names
        # would lower to one flat all-reduce over the product group
        for ax in reversed(axes):
            x = jax.lax.psum(x, ax)
        return x

    def local(state_shard, prev_commit_shard):
        v = check_invariants(state_shard, prev_commit_shard,
                             zero_violations())
        return jax.tree.map(_reduce, v)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(state_specs, P(None, axes)),
        out_specs=jax.tree.map(lambda _: P(), zero_violations()),
        check_rep=False,
    )
    return jax.jit(fn)


def build_scan_rounds(cfg: RaftConfig, spec: Spec, mesh: Mesh | None, rounds: int,
                      use_shard_map: bool = False):
    """Fixed-schedule driver: scan `rounds` lockstep rounds entirely on
    device with a constant per-round input (the benchmark hot loop — no
    host round-trips, mirroring the reference's node.run select loop staying
    in one goroutine).

    Returns jitted fn(state, inbox, prop_len, prop_data, prop_type, ri_ctx,
    do_hup, do_tick, keep_mask) -> (state, inbox).
    """
    round_fn = build_round(cfg, spec)

    def many(state, inbox, prop_len, prop_data, prop_type, ri_ctx, do_hup,
             do_tick, keep_mask):
        def body(carry, _):
            st, ib = carry
            st, ib = round_fn(
                st, ib, prop_len, prop_data, prop_type, ri_ctx, do_hup,
                do_tick, keep_mask,
            )
            return (st, ib), ()

        (state, inbox), _ = jax.lax.scan(
            body, (state, inbox), None, length=rounds
        )
        return state, inbox

    if mesh is None:
        # donate the carried fleet state: the driver never reuses the
        # previous round's buffers, and at 1M groups they are GBs of HBM
        return jax.jit(many, donate_argnums=(0, 1))
    if use_shard_map:
        in_specs = fleet_in_specs(cfg, spec, mesh)
        fn = shard_map(
            many,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=(in_specs[0], in_specs[1]),
            check_rep=False,
        )
        return jax.jit(fn, donate_argnums=(0, 1))

    def constrained(*args):
        args = tuple(_constrain(mesh, a) for a in args)
        return many(*args)

    return jax.jit(constrained, donate_argnums=(0, 1))
