"""pkg/wait parity: id-keyed and logical-deadline waiters.

``Wait`` (pkg/wait/wait.go:31-41) matches apply results to the requests
blocked on them: Register(id) hands back a waiter, Trigger(id, value)
completes it. ``WaitTime`` (pkg/wait/wait_time.go:18-27) completes every
waiter at or before a triggered logical deadline — the v3 server uses it
for read-index waits keyed by applied index.

Channels become :class:`Waiter` objects (threading.Event + value):
``wait()`` blocks, ``done`` / ``value`` poll — both usable from the
synchronous test harness and the embed tick thread.
"""
from __future__ import annotations

import threading


class Waiter:
    __slots__ = ("_ev", "value")

    def __init__(self, done: bool = False):
        self._ev = threading.Event()
        self.value = None
        if done:
            self._ev.set()

    @property
    def done(self) -> bool:
        return self._ev.is_set()

    def wait(self, timeout: float | None = None):
        if not self._ev.wait(timeout):
            raise TimeoutError("waiter timed out")
        return self.value

    def _complete(self, value) -> None:
        self.value = value
        self._ev.set()


class Wait:
    """wait.New() (wait.go:52-60); the 64-way striping collapses — one
    dict + lock serves the in-process scale."""

    def __init__(self):
        self._l = threading.Lock()
        self._m: dict[int, Waiter] = {}

    def register(self, id: int) -> Waiter:
        with self._l:
            if id in self._m:
                raise ValueError(f"duplicate id {id:x}")
            w = self._m[id] = Waiter()
            return w

    def trigger(self, id: int, value) -> None:
        with self._l:
            w = self._m.pop(id, None)
        if w is not None:
            w._complete(value)

    def is_registered(self, id: int) -> bool:
        with self._l:
            return id in self._m


class WaitTime:
    """wait.NewTimeList() (wait_time.go:37-67): Wait(deadline) completes
    once Trigger is called with deadline >= it."""

    def __init__(self):
        self._l = threading.Lock()
        self._last = 0
        self._m: dict[int, Waiter] = {}

    def wait(self, deadline: int) -> Waiter:
        with self._l:
            if self._last >= deadline:
                return Waiter(done=True)
            w = self._m.get(deadline)
            if w is None:
                w = self._m[deadline] = Waiter()
            return w

    def trigger(self, deadline: int) -> None:
        with self._l:
            self._last = max(self._last, deadline)
            due = [d for d in self._m if d <= deadline]
            ws = [self._m.pop(d) for d in due]
        for w in ws:
            w._complete(None)
