"""v2store unit tests — behavior pinned to server/etcdserver/api/v2store
store_test.go / store_ttl_test.go / watcher_test.go scenarios."""
import pytest

from etcd_tpu.server.v2store import (
    EcodeDirNotEmpty,
    EcodeEventIndexCleared,
    EcodeKeyNotFound,
    EcodeNodeExist,
    EcodeNotDir,
    EcodeNotFile,
    EcodeRootROnly,
    EcodeTestFailed,
    V2Error,
    V2Store,
)


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture
def s():
    return V2Store(clock=FakeClock())


def code(excinfo) -> int:
    return excinfo.value.code


# ------------------------------------------------------------- basic ops

def test_create_and_get(s):
    e = s.create("/foo", value="bar")
    assert e.action == "create"
    assert e.node["key"] == "/foo"
    assert e.node["value"] == "bar"
    assert e.node["createdIndex"] == 1
    assert e.etcd_index == 1
    g = s.get("/foo")
    assert g.action == "get"
    assert g.node["value"] == "bar"
    assert g.etcd_index == 1


def test_create_exists_fails(s):
    s.create("/foo", value="bar")
    with pytest.raises(V2Error) as ei:
        s.create("/foo", value="baz")
    assert code(ei) == EcodeNodeExist


def test_create_intermediate_dirs(s):
    e = s.create("/a/b/c", value="v")
    assert e.node["key"] == "/a/b/c"
    g = s.get("/a", recursive=True)
    assert g.node["dir"] is True
    assert g.node["nodes"][0]["key"] == "/a/b"


def test_create_through_file_fails(s):
    s.create("/f", value="v")
    with pytest.raises(V2Error) as ei:
        s.create("/f/child", value="v")
    assert code(ei) == EcodeNotDir


def test_get_missing(s):
    with pytest.raises(V2Error) as ei:
        s.get("/nope")
    assert code(ei) == EcodeKeyNotFound
    assert ei.value.cause == "/nope"


def test_get_dir_sorted_hides_hidden(s):
    s.create("/d", dir=True)
    s.create("/d/z", value="1")
    s.create("/d/a", value="2")
    s.create("/d/_hidden", value="3")
    g = s.get("/d", recursive=True, sorted_=True)
    keys = [n["key"] for n in g.node["nodes"]]
    assert keys == ["/d/a", "/d/z"]  # sorted, hidden skipped


def test_set_creates_then_replaces(s):
    e1 = s.set("/foo", value="v1")
    assert e1.action == "set"
    assert e1.prev_node is None
    assert e1.is_created()
    e2 = s.set("/foo", value="v2")
    assert e2.prev_node["value"] == "v1"
    assert not e2.is_created()
    assert e2.node["modifiedIndex"] == 2
    assert e2.node["createdIndex"] == 2  # set replaces the node


def test_set_on_dir_fails(s):
    s.create("/d", dir=True)
    with pytest.raises(V2Error) as ei:
        s.set("/d", value="v")
    assert code(ei) == EcodeNotFile


def test_update_value_keeps_created_index(s):
    s.create("/foo", value="v1")
    e = s.update("/foo", "v2")
    assert e.action == "update"
    assert e.node["createdIndex"] == 1
    assert e.node["modifiedIndex"] == 2
    assert e.prev_node["value"] == "v1"


def test_update_missing_and_dir(s):
    with pytest.raises(V2Error) as ei:
        s.update("/nope", "v")
    assert code(ei) == EcodeKeyNotFound
    s.create("/d", dir=True)
    with pytest.raises(V2Error) as ei:
        s.update("/d", "")
    assert code(ei) == EcodeNotFile


def test_root_read_only(s):
    for fn in (lambda: s.set("/", value="v"),
               lambda: s.delete("/", dir=True, recursive=True),
               lambda: s.update("/", "v"),
               lambda: s.compare_and_swap("/", "", 0, "v")):
        with pytest.raises(V2Error) as ei:
            fn()
        assert code(ei) == EcodeRootROnly


def test_delete_file_and_dir(s):
    s.create("/foo", value="v")
    e = s.delete("/foo")
    assert e.action == "delete"
    assert e.prev_node["value"] == "v"
    s.create("/d/x", value="v")
    with pytest.raises(V2Error) as ei:
        s.delete("/d")  # dir without dir flag
    assert code(ei) == EcodeNotFile
    with pytest.raises(V2Error) as ei:
        s.delete("/d", dir=True)  # non-empty without recursive
    assert code(ei) == EcodeDirNotEmpty
    e = s.delete("/d", recursive=True)  # recursive implies dir
    assert e.node["dir"] is True
    with pytest.raises(V2Error):
        s.get("/d/x")


def test_cas(s):
    s.create("/foo", value="v1")
    e = s.compare_and_swap("/foo", "v1", 0, "v2")
    assert e.action == "compareAndSwap"
    assert e.node["value"] == "v2"
    with pytest.raises(V2Error) as ei:
        s.compare_and_swap("/foo", "bad", 0, "v3")
    assert code(ei) == EcodeTestFailed
    assert "[bad != v2]" in ei.value.cause
    with pytest.raises(V2Error) as ei:
        s.compare_and_swap("/foo", "", 999, "v3")
    assert code(ei) == EcodeTestFailed
    assert "[999 != 2]" in ei.value.cause


def test_cas_both_wildcards_swap(s):
    s.create("/foo", value="v1")
    e = s.compare_and_swap("/foo", "", 0, "v2")
    assert e.node["value"] == "v2"


def test_cad(s):
    s.create("/foo", value="v1")
    with pytest.raises(V2Error) as ei:
        s.compare_and_delete("/foo", "bad", 0)
    assert code(ei) == EcodeTestFailed
    e = s.compare_and_delete("/foo", "v1", 0)
    assert e.action == "compareAndDelete"
    with pytest.raises(V2Error):
        s.get("/foo")
    s.create("/d", dir=True)
    with pytest.raises(V2Error) as ei:
        s.compare_and_delete("/d", "", 0)
    assert code(ei) == EcodeNotFile


def test_create_in_order(s):
    s.create("/q", dir=True)
    e1 = s.create("/q", unique=True, value="a")
    e2 = s.create("/q", unique=True, value="b")
    k1, k2 = e1.node["key"], e2.node["key"]
    assert k1 < k2  # zero-padded index names sort in creation order
    assert k1.split("/")[-1] == format(2, "020d")
    g = s.get("/q", recursive=True, sorted_=True)
    assert [n["value"] for n in g.node["nodes"]] == ["a", "b"]


# --------------------------------------------------------------- TTL

def test_ttl_expire(s):
    clk = s.clock
    s.create("/foo", value="v", expire_time=clk.t + 5)
    g = s.get("/foo")
    assert g.node["ttl"] == 5
    clk.advance(3)
    assert s.get("/foo").node["ttl"] == 2
    s.delete_expired_keys(clk.t)
    assert s.get("/foo").node["value"] == "v"  # not yet
    clk.advance(3)
    s.delete_expired_keys(clk.t)
    with pytest.raises(V2Error) as ei:
        s.get("/foo")
    assert code(ei) == EcodeKeyNotFound
    assert s.stats.counters["expireCount"] == 1


def test_ttl_update_to_permanent(s):
    clk = s.clock
    s.create("/foo", value="v", expire_time=clk.t + 5)
    s.update("/foo", "v2")  # no TTL in update → becomes permanent
    clk.advance(10)
    s.delete_expired_keys(clk.t)
    assert s.get("/foo").node["value"] == "v2"
    assert not s.has_ttl_keys()


def test_ttl_refresh_keeps_value(s):
    clk = s.clock
    s.create("/foo", value="v", expire_time=clk.t + 2)
    e = s.update("/foo", "", expire_time=clk.t + 100, refresh=True)
    assert e.refresh
    assert s.get("/foo").node["value"] == "v"  # refresh keeps value
    clk.advance(50)
    s.delete_expired_keys(clk.t)
    assert s.get("/foo").node["value"] == "v"


def test_expire_dir_notifies_inner_watcher(s):
    clk = s.clock
    s.create("/d", dir=True, expire_time=clk.t + 1)
    s.create("/d/k", value="v")
    w = s.watch("/d/k")
    clk.advance(2)
    s.delete_expired_keys(clk.t)
    ev = w.poll()
    assert ev is not None
    assert ev.action == "expire"


# --------------------------------------------------------------- watch

def test_watch_future_event(s):
    w = s.watch("/foo")
    assert w.poll() is None
    s.create("/foo", value="v")
    ev = w.poll()
    assert ev.action == "create"
    assert ev.node["key"] == "/foo"
    # one-shot watcher: removed after firing
    s.set("/foo", value="v2")
    assert w.poll() is None


def test_watch_from_history(s):
    s.create("/foo", value="v1")
    s.set("/foo", value="v2")
    w = s.watch("/foo", since_index=1)
    ev = w.poll()
    assert ev.node["modifiedIndex"] == 1
    assert ev.action == "create"


def test_watch_recursive(s):
    w = s.watch("/d", recursive=True, stream=True)
    s.create("/d/a", value="1")
    s.create("/d/b", value="2")
    assert w.poll().node["key"] == "/d/a"
    assert w.poll().node["key"] == "/d/b"


def test_watch_hidden_not_notified(s):
    w = s.watch("/d", recursive=True, stream=True)
    s.create("/d/_secret", value="1")
    assert w.poll() is None
    # but watching the hidden path directly works
    w2 = s.watch("/d/_secret")
    s.set("/d/_secret", value="2")
    assert w2.poll() is not None


def test_watch_delete_dir_notifies_children_watchers(s):
    s.create("/d/k", value="v")
    w = s.watch("/d/k")
    s.delete("/d", recursive=True)
    ev = w.poll()
    assert ev.action == "delete"


def test_watch_index_cleared(s):
    for i in range(1, 1100):
        s.set(f"/k{i}", value="v")
    with pytest.raises(V2Error) as ei:
        s.watch("/k1", since_index=1)
    assert code(ei) == EcodeEventIndexCleared


def test_watch_history_scan_recursive_prefix(s):
    s.create("/d/sub/x", value="v")
    w = s.watch("/d", recursive=True, since_index=1)
    ev = w.poll()
    assert ev.node["key"] == "/d/sub/x"


# ------------------------------------------------- persistence / clone

def test_save_recovery_roundtrip(s):
    clk = s.clock
    s.create("/a/b", value="v1")
    s.create("/ttl", value="v2", expire_time=clk.t + 5)
    s.create("/d", dir=True)
    blob = s.save()
    s2 = V2Store(clock=clk)
    s2.recovery(blob)
    assert s2.index() == s.index()
    assert s2.get("/a/b").node["value"] == "v1"
    assert s2.get("/ttl").node["ttl"] == 5
    assert s2.has_ttl_keys()
    clk.advance(10)
    s2.delete_expired_keys(clk.t)
    with pytest.raises(V2Error):
        s2.get("/ttl")
    assert s2.get("/a/b").node["value"] == "v1"


def test_clone_independent(s):
    s.create("/foo", value="v")
    c = s.clone()
    s.set("/foo", value="v2")
    assert c.get("/foo").node["value"] == "v"
    assert c.index() == 1


def test_json_stats(s):
    s.create("/foo", value="v")
    with pytest.raises(V2Error):
        s.get("/nope")
    st = s.json_stats()
    assert st["createSuccess"] == 1
    assert st["getsFail"] == 1


def test_namespaces_readonly():
    s = V2Store(namespaces=("/0", "/1"))
    assert s.get("/0").node["dir"] is True
    with pytest.raises(V2Error) as ei:
        s.set("/0", value="v")
    assert code(ei) == EcodeRootROnly
    s.set("/0/key", value="v")  # children are writable


def test_event_index_semantics(s):
    """EtcdIndex on reads = store index at read time, not node index."""
    s.create("/a", value="1")
    s.create("/b", value="2")
    g = s.get("/a")
    assert g.etcd_index == 2
    assert g.node["modifiedIndex"] == 1
