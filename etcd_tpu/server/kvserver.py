"""Replicated KV server runtime over the batched consensus engine.

The reference's ``EtcdServer`` (server/etcdserver/server.go:202) owns the
raft node, MVCC, lessor and auth store, routes client requests through
consensus (v3_server.go:643 processInternalRaftRequestOnce: register wait id
-> Propose -> block until applied), applies committed entries to the state
machine (server.go:1829-1944), and serves linearizable reads via ReadIndex
(v3_server.go:709-879).

Here one :class:`EtcdCluster` drives cluster ``c`` of a batched engine; each
member has its own :class:`MemberState` (watchable MVCC + lessor + auth),
exactly like each etcd process has its own bbolt. Entry payloads live in a
host-side request table keyed by the int32 word the device replicates — the
"payloadRef" scheme of SURVEY.md §7: the device log replicates references,
the host resolves them at apply time. Apply results flow back through a
wait-map (pkg/wait/wait.go:33-41 analog) to the blocked caller.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any

import numpy as np

from etcd_tpu.harness.cluster import Cluster
from etcd_tpu.models import confchange as ccdev
from etcd_tpu.models.changer import Changer, Config as HostConfig, ConfChangeError
from etcd_tpu.server.auth import AuthStore
from etcd_tpu.server.lease import ErrLeaseNotFound, Lessor
from etcd_tpu.server.mvcc import ErrCompacted, ErrFutureRev, KeyValue
from etcd_tpu.server.version import (
    DowngradeInfo,
    MIN_CLUSTER_VERSION,
    SERVER_VERSION,
    VersionMonitor,
    allowed_downgrade_version,
    cluster_version_str,
    detect_downgrade,
    major_minor,
)
from etcd_tpu.server.v2store import V2Store
from etcd_tpu.server.watch import WatchableStore
from etcd_tpu.types import ENTRY_CONF_CHANGE, NONE_ID, ROLE_LEADER


class ServerError(Exception):
    pass


class ErrNoLeader(ServerError):
    pass


class ErrTimeout(ServerError):
    pass


class ErrTooManyRequests(ServerError):
    """commit-apply gap backpressure (v3_server.go:45,646)."""


class ErrNoSpace(ServerError):
    """NOSPACE alarm raised through consensus (api/v3alarm)."""


class ErrCorrupt(ServerError):
    pass


class ErrInvalidDowngradeTargetVersion(ServerError):
    """target must be exactly one minor below the cluster version
    (v3_server.go:936-938)."""


class ErrDowngradeInProcess(ServerError):
    """a downgrade job is already live (v3_server.go:941-944)."""


class ErrNoInflightDowngrade(ServerError):
    """cancel with no live downgrade job (v3_server.go:979-983)."""


class ErrClusterVersionUnavailable(ServerError):
    """cluster version not yet decided (v3_server.go:930-932)."""


@dataclasses.dataclass
class ResponseHeader:
    cluster_id: int
    member_id: int
    revision: int
    raft_term: int


@dataclasses.dataclass
class Op:
    """clientv3.Op analog (client/v3/op.go)."""

    type: str  # "put" | "range" | "delete"
    key: bytes
    value: bytes = b""
    range_end: bytes | None = None
    lease: int = 0
    prev_kv: bool = False
    limit: int = 0
    rev: int = 0
    count_only: bool = False


@dataclasses.dataclass
class Compare:
    """clientv3.Compare (client/v3/compare.go): target in
    {version,create,mod,value,lease}, result in {=,!=,>,<}."""

    key: bytes
    target: str
    result: str
    value: Any


@dataclasses.dataclass
class MemberState:
    """One member's applied state machine bundle."""

    store: WatchableStore
    lessor: Lessor
    auth: AuthStore
    applied_index: int = 0
    # wait-map: req word -> apply result (pkg/wait analog)
    results: dict[int, Any] = dataclasses.field(default_factory=dict)
    alarms: set[str] = dataclasses.field(default_factory=set)
    # durable backend (bbolt analog; None = memory-only member)
    backend: Any = None
    persisted_rev: int = 0
    persisted_compact: int = 0
    # consistent index actually fsync'd — the replay floor after a crash
    durable_index: int = 0
    crashed: bool = False  # host process down: skip apply + donor duty
    _persist_sig: Any = None  # last persisted (applied, rev, compact)
    # this member binary's version (version.Version; overridable per
    # member for mixed-version fleets) and its APPLIED view of the
    # negotiated cluster version + downgrade job — replicated state,
    # set only through consensus (cluster.go SetVersion/SetDowngradeInfo)
    server_version: str = SERVER_VERSION
    cluster_version: str | None = None
    downgrade: DowngradeInfo = dataclasses.field(default_factory=DowngradeInfo)
    # legacy v2 applied state machine (api/v2store), mutated only by
    # committed kind="v2" entries (the applyV2Request path, apply_v2.go)
    v2store: "V2Store" = dataclasses.field(default_factory=lambda: V2Store())


class EtcdCluster:
    """Drives one batched cluster as an etcd-like multi-member deployment."""

    MAX_APPLY_WAIT_ROUNDS = 64
    MAX_GAP = 5000  # maxGapBetweenApplyAndCommitIndex (v3_server.go:45)

    def __init__(
        self,
        n_members: int = 3,
        cluster: Cluster | None = None,
        c: int = 0,
        quota_bytes: int = 0,
        lease_min_ttl: int = 1,
        data_dir: str | None = None,
        auth_token: str = "simple",
        auth_jwt_key: bytes | None = None,
        durable_proposes: bool = False,
        apply_plane: str = "host",
        kv_keys: int = 64,
        telemetry: bool = False,
        blackbox: bool = False,
    ):
        # telemetry=True attaches the fleet telemetry plane to the
        # backing Cluster (harness/cluster.py): /metrics then serves the
        # latency-histogram families (v3rpc) from it; blackbox=True adds
        # the per-round EventRing (models/blackbox.py), exportable as a
        # Chrome trace alongside the host request spans below. Ignored
        # when an explicit `cluster` is injected — its owner decides.
        self.cl = cluster or Cluster(n_members=n_members,
                                     telemetry=telemetry,
                                     blackbox=blackbox)
        # acknowledged ⇒ on disk: fsync the members' backends before a
        # propose returns (the reference gets this from WAL MustSync
        # before the Ready is acked, storage.go; here the device ring
        # is the log and dies with the process, so the durable floor is
        # the backend record log). Off by default for in-process
        # harness/test clusters; embed turns it on unless the operator
        # passes --unsafe-no-fsync.
        self.durable_proposes = durable_proposes
        self.c = c
        self.M = self.cl.spec.M
        self.quota_bytes = quota_bytes
        self.requests: dict[int, dict] = {}  # word -> request payload
        self._next_word = 1
        self.data_dir = data_dir
        self._gc_floor = 0  # lowest applied index with payloads retained
        # --auth-token analog (embed.Config.AuthToken): every member (and
        # every restart incarnation) shares the provider spec + signing key
        self.auth_token = auth_token
        self.auth_jwt_key = auth_jwt_key
        # armed by embed's ticker (utils/contention.py): late host ticks
        # are the TPU analog of the reference's late leader heartbeats
        self.contention = None
        # slow-request counters served by /metrics
        # (etcd_server_slow_apply_total / etcd_server_slow_read_indexes_
        # total — the reference's applyTook>warningApplyDuration and
        # slowReadIndex signals, server.go / v3_server.go)
        self.slow_apply_total = 0
        self.slow_read_index_total = 0
        # completed request spans (Trace.to_span dicts) for the Chrome
        # trace exporter; bounded ring so long-lived servers don't grow
        self.req_spans: list[dict] = []
        # per-member binary-version overrides for mixed-version fleets
        # (the reference's rolling binary swap); applies at construction
        # AND at restart-from-disk (see _member_from_backend)
        self.server_versions: dict[int, str] = {}
        # wall clock for v2 TTL stamping (injectable for deterministic
        # TTL tests; replicated state never reads it directly — only
        # propose-time stamps do)
        import time as _time

        self.v2_now = _time.time
        # apply_plane="device": each member's KV store is one lane of the
        # device-resident apply plane (etcd_tpu/device_mvcc) behind the
        # DeviceBackedStore facade — puts/deletes/compactions dispatch as
        # int32 op words, reads/digests come back from device tensors,
        # and watch events fan out of the per-op delta readbacks.  The
        # host plane stays the default; the device plane serves the
        # canonical key space only (scheme.key_bytes) and is exercised
        # end-to-end by tests/test_device_mvcc.py.
        if apply_plane not in ("host", "device"):
            raise ServerError(f"unknown apply_plane {apply_plane!r}")
        self.apply_plane = apply_plane
        self.device_plane = None
        if apply_plane == "device":
            if data_dir:
                raise ServerError(
                    "apply_plane='device' has no backend persistence path "
                    "yet; the durable floor is the device snapshot tier"
                )
            from etcd_tpu.device_mvcc import DevicePlane, KVSpec

            self.device_plane = DevicePlane(KVSpec(keys=kv_keys), C=self.M)
        self.members = [
            MemberState(self._fresh_store(m), Lessor(lease_min_ttl),
                        self._new_auth())
            for m in range(self.M)
        ]
        if data_dir:
            import os

            from etcd_tpu.storage.backend import Backend

            os.makedirs(data_dir, exist_ok=True)
            for m, ms in enumerate(self.members):
                # fresh incarnation: any file from a previous cluster in
                # this directory must not leak phantom revisions
                ms.backend = Backend(self._backend_path(m), fresh=True)
        self._root_token: str | None = None

    @staticmethod
    def member_db_path(data_dir: str, m: int) -> str:
        import os

        return os.path.join(data_dir, f"member{m}.db")

    def _backend_path(self, m: int) -> str:
        return self.member_db_path(self.data_dir, m)

    def _new_auth(self) -> AuthStore:
        return AuthStore(token=self.auth_token, jwt_key=self.auth_jwt_key)

    def _fresh_store(self, m: int) -> WatchableStore:
        """An empty applied KV store for member m — a host MVCCStore, or
        (device plane) the member's device lane wiped back to boot state
        (a crash drops the applied state machine either way; recovery is
        ring replay or a peer snapshot through _pump)."""
        if self.device_plane is None:
            return WatchableStore()
        from etcd_tpu.server.mvcc import DeviceBackedStore

        self.device_plane.load_lane(m, {}, 1, 0)
        return WatchableStore(DeviceBackedStore(self.device_plane, m))

    # ------------------------------------------------------------------ raft
    def leader(self) -> int:
        return self.cl.leader(self.c)

    def ensure_leader(self) -> int:
        lead = self.leader()
        if lead == NONE_ID:
            for _ in range(40):
                self.tick()
                lead = self.leader()
                if lead != NONE_ID:
                    break
        if lead == NONE_ID:
            raise ErrNoLeader()
        return lead

    def tick(self, lease_clock: bool = True) -> None:
        """One raft tick. `lease_clock=False` advances only the raft
        timers: lease/auth TTLs are denominated in SECONDS like the
        reference (lease/lessor.go), so a sub-second raft ticker (e.g.
        embed's 100ms loop) must advance the lease clock on a 1s cadence,
        not per raft tick."""
        self.cl.step(tick=True)
        self._pump()
        if lease_clock:
            self.advance_lease_clock()

    def advance_lease_clock(self) -> None:
        """One lease-clock second: TTL countdowns + expiry proposals."""
        for ms in self.members:
            ms.lessor.tick()
            ms.auth.tick()
        self._expire_leases()

    def step(self) -> None:
        self.cl.step()
        self._pump()

    def sync_for_shutdown(self, max_rounds: int = 16) -> None:
        """Drain commit -> apply -> persist before a clean close, so every
        member's backend reaches the committed front. A reference follower
        gets this durability from WAL replay of its committed tail
        (storage.go MustSync + bootstrapWithWAL); here the device ring is
        the log and dies with the process, so the drain runs eagerly.

        The staged batches are then COMMITTED: _persist only flushes at
        the batch threshold, so a short-lived cluster that drained its
        applies could still lose the whole applied_meta record to a
        subsequent crash (found by test_restart_refused_mid_downgrade —
        the restart recovered via peer snapshot instead of its own disk,
        masking the mustDetectDowngrade boot check)."""
        for _ in range(max_rounds):
            live = [
                ms.applied_index for ms in self.members if not ms.crashed
            ]
            if len(set(live)) <= 1:
                break
            self.step()
        self.commit_backends()

    def stabilize(self, max_rounds: int = 64) -> None:
        self.cl.step()
        self._pump()
        for _ in range(max_rounds):
            if self.cl.eng.pending_messages() == 0:
                break
            self.cl.step()
            self._pump()

    # -------------------------------------------------------------- applying
    def _pump(self) -> None:
        """Drain newly-applied entries device->host for every member
        (the applyAll path, server.go:903-1104)."""
        s = self.cl.s
        c = self.c
        applied = np.asarray(s.applied[..., c])
        last = np.asarray(s.last_index[..., c])
        snap = np.asarray(s.snap_index[..., c])
        terms = np.asarray(s.log_term[..., c])
        datas = np.asarray(s.log_data[..., c])
        types = np.asarray(s.log_type[..., c])
        L = self.cl.spec.L

        def apply_range(m, ms, lo, hi):
            for idx in range(lo + 1, hi + 1):
                sl = (idx - 1) % L
                self._apply_entry(
                    m, ms, idx, int(types[m, sl]), int(datas[m, sl]),
                    int(terms[m, sl]),
                )
            ms.applied_index = hi

        # pass 1: members whose ring still covers their gap — pumping them
        # first means their fresh host state is available as snapshot donor
        # material for pass 2
        gapped = []
        for m, ms in enumerate(self.members):
            if ms.crashed:
                continue
            hi, lo = int(applied[m]), ms.applied_index
            if hi <= lo:
                continue
            # a member is gapped when the ring compacted past its cursor
            # OR the host payload table was GC'd below it (a restarted
            # member replaying from 0): ring replay would silently skip
            # entries — install a peer snapshot instead
            if int(snap[m]) > lo or lo < self._gc_floor:
                gapped.append(m)
                continue
            apply_range(m, ms, lo, hi)
        # pass 2: the device compacted past these members' host-applied
        # cursors — entries (lo, snap] are gone from the ring. Install a
        # peer's state-machine snapshot first (the applySnapshot path,
        # server.go:925-1061); silently skipping the gap would diverge this
        # member's MVCC from its peers.
        for m in gapped:
            ms = self.members[m]
            self._install_peer_snapshot(
                m, ms, max(int(snap[m]), self._gc_floor)
            )
            hi, lo = int(applied[m]), ms.applied_index
            if hi > lo:
                apply_range(m, ms, lo, hi)
        terms_now = np.asarray(s.term[..., c])
        for m, ms in enumerate(self.members):
            if ms.backend is not None and not ms.crashed:
                self._persist(ms, int(terms_now[m]))
        self._gc_requests()

    def commit_backends(self) -> None:
        """Flush + fsync every live member's staged batch so the durable
        floor reaches the current applied front (the per-ack half of
        sync_for_shutdown's drain)."""
        for ms in self.members:
            if ms.backend is not None and not ms.crashed:
                ms.backend.commit()
                ms.durable_index = ms.applied_index

    def _persist(self, ms: MemberState, term: int) -> None:
        """Write the apply batch behind the member: new MVCC revisions +
        one atomic applied-meta record (consistent index, cursors, lease/
        auth/alarm snapshots) — the batchTx + cindex discipline of
        backend/batch_tx.go + cindex/cindex.go:30-38. Flushing happens on
        the backend's batch limit; a crash between commits rolls the
        member back to the last committed point and WAL/ring replay
        resumes from its consistent index."""
        from etcd_tpu.storage import schema
        from etcd_tpu.utils import failpoints

        kv = ms.store.kv
        sig = (ms.applied_index, kv.current_rev, kv.compact_rev)
        if sig == getattr(ms, "_persist_sig", None):
            return  # nothing applied since the last persist: no-op
        # gofail raftBeforeSave marker (etcdserver/raft.go:221): the batch
        # is about to be staged behind this member
        failpoints.fire("raftBeforeSave")
        if kv.compact_rev > ms.persisted_compact:
            schema.persist_compaction(ms.backend, kv)
            ms.persisted_compact = kv.compact_rev
        ms.persisted_rev = schema.persist_mvcc_delta(
            ms.backend, kv, ms.persisted_rev
        )
        schema.save_applied_meta(
            ms.backend,
            index=ms.applied_index,
            term=term,
            store=kv,
            lease_snap=ms.lessor.to_snapshot(),
            auth_snap=ms.auth.to_snapshot(),
            alarms=ms.alarms,
            cluster_version=ms.cluster_version,
            downgrade=ms.downgrade.to_dict(),
            v2=ms.v2store.save(),
        )
        # sig records success only after the batch is fully staged: a crash
        # at any marker above re-stages the whole batch on the next pump
        ms._persist_sig = sig
        # gofail raftAfterSave (etcdserver/raft.go:228): staged but not
        # necessarily fsync'd — a crash here loses the uncommitted batch
        failpoints.fire("raftAfterSave")
        # half-full batch -> flush now so the durable floor advances and
        # the payload table can GC (the 100ms batchInterval analog)
        if ms.backend._pending_ops >= ms.backend.batch_limit // 2:
            ms.backend.commit()
        if not ms.backend._pending_ops:
            ms.durable_index = ms.applied_index

    def crash_member(self, m: int) -> None:
        """Simulate a member process crash: all host applied state is
        dropped; only what the backend committed survives on disk."""
        from etcd_tpu.utils.logging import get_logger

        get_logger().warning("member %d crashed (host state dropped)", m)
        ms = self.members[m]
        if ms.backend is not None:
            ms.backend._f.close()  # no commit: the pending batch is lost
        husk = MemberState(
            self._fresh_store(m), Lessor(ms.lessor.min_ttl), self._new_auth()
        )
        husk.crashed = True
        self.members[m] = husk

    def restart_member_from_disk(self, m: int) -> None:
        """Rebuild a member's applied state machine from its backend (the
        bootstrapBackend path, server/etcdserver/bootstrap.go:145): MVCC
        from the key bucket trimmed to the atomic applied-meta record,
        lease/auth/alarms from that record, applied cursor = consistent
        index — entries <= cindex replay as no-ops (dedup across restart,
        server.go:1879-1885)."""
        from etcd_tpu.storage import schema
        from etcd_tpu.storage.backend import Backend

        if self.data_dir is None:
            # memory-only member: nothing on disk — come back empty and
            # catch up from the ring / a peer snapshot through _pump. The
            # restarting binary keeps its override version, and the boot
            # check runs AFTER catch-up against whatever cluster state the
            # peer snapshot restored (the bootstrapExistingClusterNoWAL
            # case of mustDetectDowngrade).
            husk = MemberState(
                self._fresh_store(m),
                Lessor(self.members[m].lessor.min_ttl), self._new_auth(),
            )
            if m in self.server_versions:
                husk.server_version = self.server_versions[m]
            self.members[m] = husk
            self._pump()
            ms = self.members[m]
            try:
                detect_downgrade(
                    ms.server_version, ms.cluster_version, ms.downgrade
                )
            except Exception:
                ms.crashed = True  # refuse to serve on an illegal mix
                raise
            return

        be = Backend(self._backend_path(m))
        ms, _ = self._member_from_backend(
            be, self.members[m].lessor.min_ttl, m=m
        )
        self.members[m] = ms
        # catch up from the device ring (or a peer snapshot if compacted)
        self._pump()

    def _member_from_backend(
        self, be, lease_min_ttl: int = 1, m: int | None = None
    ) -> tuple[MemberState, dict]:
        """Rebuild one member's applied state bundle from an open backend
        (the shared tail of bootstrapBackend, bootstrap.go:145)."""
        from etcd_tpu.storage import schema

        meta = schema.load_applied_meta(be) or {
            "consistent_index": 0, "term": 0, "current_rev": 1,
            "compact_rev": 0, "lease": None, "auth": None, "alarms": [],
        }
        store = schema.load_mvcc(
            be, max_rev=meta["current_rev"], compact_rev=meta["compact_rev"]
        )
        ws = WatchableStore()
        ws.restore(store)
        ms = MemberState(ws, Lessor(lease_min_ttl), self._new_auth())
        if meta["lease"] is not None:
            ms.lessor.restore(meta["lease"])
        if meta["auth"] is not None:
            ms.auth.restore(meta["auth"])
        ms.alarms = set(meta["alarms"])
        ms.applied_index = meta["consistent_index"]
        ms.backend = be
        ms.persisted_rev = store.current_rev
        ms.persisted_compact = store.compact_rev
        ms.durable_index = meta["consistent_index"]
        # recover the replicated version records (cluster.go:263-269),
        # then refuse to serve on an illegal version mix — the
        # mustDetectDowngrade boot check (downgrade.go:41-75). The
        # restarting "binary"'s version comes from the per-member
        # override map (a rolling binary swap in the reference world).
        if m is not None and m in self.server_versions:
            ms.server_version = self.server_versions[m]
        ms.cluster_version = meta.get("cluster_version")
        ms.downgrade = DowngradeInfo.from_dict(meta.get("downgrade"))
        if meta.get("v2"):
            ms.v2store.recovery(meta["v2"])
        detect_downgrade(ms.server_version, ms.cluster_version, ms.downgrade)
        return ms, meta

    @classmethod
    def boot_from_disk(
        cls,
        data_dir: str,
        n_members: int = 3,
        missing_ok: bool = False,
        uniform: bool = True,
        members: list[int] | None = None,
        **kw,
    ) -> "EtcdCluster":
        """Boot a cluster from an EXISTING data dir (the bootstrapWithWAL /
        etcdutl-restore boot path, bootstrap.go:253 +
        etcdutl/snapshot_command.go:122): each member's applied state
        machine loads from its backend, and the device raft state starts
        from a synthetic snapshot at the restored consistent index — the
        analog of the fresh WAL whose first record is the snapshot marker
        that `etcdutl snapshot restore` writes. Contrast __init__ with
        data_dir=..., which wipes for a fresh incarnation.

        ``missing_ok``: members whose backend file is absent boot empty
        and catch up from a peer snapshot — the in-process analog of
        bootstrapExistingClusterNoWAL (bootstrap.go:182): a data-less
        member joining a cluster that already has state.

        ``uniform``: require every present member at ONE consistent index
        (the etcdutl-restore contract — a restored dir is written from a
        single snapshot). Restarting a live data dir (embed's haveWAL
        path) passes False: members legitimately shut down a few applied
        entries apart, and the laggards catch up from the most advanced
        peer exactly as a slow member would at runtime.

        ``members``: which on-disk member files back each new member
        (defaults to identity). force-new-cluster passes the surviving
        member's index so a 1-member recovery can start from whichever
        data file still exists; the loaded backend stays bound to that
        file, so subsequent persists continue it."""
        import os

        from etcd_tpu.storage.backend import Backend

        ec = cls(n_members=n_members, **kw)  # memory boot; no wipe
        ec.data_dir = data_dir
        disk = members if members is not None else list(range(ec.M))
        if len(disk) != ec.M:
            raise ServerError(
                f"members maps {len(disk)} disk files onto {ec.M} members"
            )
        metas = []
        missing: list[int] = []
        for m in range(ec.M):
            path = cls.member_db_path(data_dir, disk[m])
            if missing_ok and not os.path.exists(path):
                missing.append(m)
                metas.append(None)
                continue
            be = Backend(path)
            ms, meta = ec._member_from_backend(be, m=m)
            ec.members[m] = ms
            metas.append(meta)
        present = [meta for meta in metas if meta is not None]
        if not present:
            raise ServerError(
                f"no member data found under {data_dir}; cannot join an "
                "existing cluster that has none"
            )
        idx = max(meta["consistent_index"] for meta in present)
        term = max(meta["term"] for meta in present)
        behind: list[int] = []
        for m, meta in enumerate(metas):
            if meta is not None and meta["consistent_index"] != idx:
                if uniform:
                    raise ServerError(
                        f"member {m} restored at index "
                        f"{meta['consistent_index']} != {idx}; a restored "
                        "data dir must be uniform (snapshot restore writes "
                        "every member from the same snapshot)"
                    )
                behind.append(m)
        if idx > 0:
            # synthetic device snapshot: log starts at (idx, term) with an
            # empty tail, exactly like handle_snapshot's restore field set
            # (models/raft.py:718-736) minus the config masks, which a
            # restored cluster keeps at the boot-time full-voter set
            for m in range(ec.M):
                ec.cl.set_node(
                    m, c=ec.c,
                    term=term, commit=idx, applied=idx, last_index=idx,
                    snap_index=idx, snap_term=term,
                    applied_hash=0, snap_hash=0,
                )
            ec._gc_floor = idx
        for m in missing:
            # data-less joiner: fresh backend + applied state from the
            # most advanced restored peer, then persist the baseline
            ec.members[m].backend = Backend(
                cls.member_db_path(data_dir, disk[m]), fresh=True
            )
            if idx > 0:
                ec._install_peer_snapshot(m, ec.members[m], idx)
            ec._persist(ec.members[m], term)
        for m in behind:
            # shut down a few entries behind the front: catch up the
            # applied state machine from the most advanced peer
            ec._install_peer_snapshot(m, ec.members[m], idx)
            ec._persist(ec.members[m], term)
        return ec

    def _install_peer_snapshot(self, m: int, ms: "MemberState",
                               need: int) -> None:
        """Restore member m's applied state machine from the most advanced
        peer whose snapshot covers index `need` (SendSnapshot/applySnapshot:
        rafthttp snapshot_sender.go + server.go:925). Raises ErrCorrupt if
        no peer can cover the gap — failing loudly beats silent divergence."""
        donors = [
            d for d in range(self.M)
            if d != m and not self.members[d].crashed
            and self.members[d].applied_index >= need
        ]
        if not donors:
            raise ErrCorrupt(
                f"member {m} needs applied state at index {need} but no peer "
                f"has applied that far; host state machine cannot catch up"
            )
        donor = max(donors, key=lambda d: self.members[d].applied_index)
        from etcd_tpu.utils import failpoints
        from etcd_tpu.utils.logging import get_logger

        # gofail raftBeforeApplySnap/raftAfterApplySnap
        # (etcdserver/raft.go:242,256)
        failpoints.fire("raftBeforeApplySnap")
        get_logger().info(
            "installing peer snapshot on member %d from donor %d at "
            "index %d", m, donor, self.members[donor].applied_index,
        )
        # the snapshot moves through the streamed side-channel (chunked,
        # per-chunk + total CRC — snapshot_sender.go / snap/db.go), so a
        # torn or corrupted transfer raises instead of installing
        from etcd_tpu.storage.snapstream import transfer

        self.restore_member(m, transfer(self.member_snapshot(donor)))
        failpoints.fire("raftAfterApplySnap")

    # -- state-machine snapshots (full applied state, not just KV) ----------
    def member_snapshot(self, m: int) -> dict:
        """Everything needed to reconstruct a member's applied state at its
        applied_index: MVCC + lessor + auth + alarms (the merged
        WAL-snapshot + backend `.snap.db` of snapshot_merge.go:85)."""
        ms = self.members[m]
        return {
            "applied_index": ms.applied_index,
            "term": self.cl.get("term", m, self.c),
            "kv": ms.store.kv.to_snapshot(),
            "lease": ms.lessor.to_snapshot(),
            "auth": ms.auth.to_snapshot(),
            "alarms": sorted(ms.alarms),
            # replicated version records: a snapshot-restored member must
            # not revert to "version unknown" — that would wedge
            # versions_match_target (and so monitor_downgrade) forever
            "cluster_version": ms.cluster_version,
            "downgrade": ms.downgrade.to_dict(),
            # v2 tree rides the snapshot like the reference's v2store
            # snap (server.go snapshot() marshals the v2 store)
            "v2": ms.v2store.save(),
        }

    def restore_member(self, m: int, snap: dict) -> None:
        from etcd_tpu.server.mvcc import DeviceBackedStore, MVCCStore

        ms = self.members[m]
        if self.device_plane is not None:
            # install into the device lane, then re-sync watchers against
            # the same facade object (the applySnapshot path, device form)
            kv = ms.store.kv
            if not isinstance(kv, DeviceBackedStore):
                kv = DeviceBackedStore(self.device_plane, m)
            kv.load_snapshot(snap["kv"])
            ms.store.restore(kv)
        else:
            ms.store.restore(MVCCStore.from_snapshot(snap["kv"]))
        ms.lessor.restore(snap["lease"])
        ms.auth.restore(snap["auth"])
        ms.alarms = set(snap["alarms"])
        ms.applied_index = snap["applied_index"]
        ms.cluster_version = snap.get("cluster_version")
        ms.downgrade = DowngradeInfo.from_dict(snap.get("downgrade"))
        if snap.get("v2"):
            ms.v2store.recovery(snap["v2"])
        ms.results.clear()

    def _gc_requests(self) -> None:
        """Drop request payloads every configured member has applied (the
        analog of log compaction for the host-side payload table)."""
        ref = max(range(self.M), key=lambda m: self.members[m].applied_index)
        s = self.cl.s
        conf = (
            np.asarray(s.voters[ref, ..., self.c])
            | np.asarray(s.voters_out[ref, ..., self.c])
            | np.asarray(s.learners[ref, ..., self.c])
        )
        # The floor is what's DURABLE per member: a backend member may
        # restart and replay everything past its last committed consistent
        # index, so its payloads must survive until that index advances
        # (the WAL-retained-until-snapshot contract). A crashed husk pins
        # the floor at 0 until restart.
        def _floor(ms: MemberState) -> int:
            if ms.crashed:
                return 0
            if ms.backend is not None:
                return min(ms.applied_index, ms.durable_index)
            return ms.applied_index

        floor = min(_floor(self.members[m]) for m in range(self.M) if conf[m])
        self._gc_floor = max(self._gc_floor, floor)
        for word in [
            w for w, r in self.requests.items()
            if r.get("_index", 1 << 62) <= floor
        ]:
            del self.requests[word]

    def _apply_entry(self, m, ms, index, etype, word, term) -> None:
        if etype == ENTRY_CONF_CHANGE:
            return  # device applied it to the config masks already
        if word == 0:
            return  # empty (leader-election) entry
        req = self.requests.get(word)
        if req is None:
            return  # foreign/unknown ref (e.g. replay after restart)
        req["_index"] = index  # for payload-table GC once all members apply
        t0 = time.perf_counter()
        try:
            res = self._dispatch(m, ms, req)
        except (ServerError, Exception) as e:  # applier must never crash
            res = e
        dt = time.perf_counter() - t0
        if dt > self.SLOW_APPLY_THRESHOLD_S:
            # the applyTook > warningApplyDuration signal
            # (etcdserver/server.go) behind etcd_server_slow_apply_total
            self.slow_apply_total += 1
            from etcd_tpu.utils.logging import get_logger

            get_logger().warning(
                "slow apply: member=%d kind=%s index=%d took %.3fs",
                m, req.get("kind", "?"), index, dt)
        # only the serving member's wait-map entry has a consumer; recording
        # results on every member would leak one entry per request per peer
        if m == req.get("_serve_m"):
            ms.results[word] = res

    # dispatch of InternalRaftRequest (apply.go:64-99 applierV3 surface)
    def _dispatch(self, m: int, ms: MemberState, req: dict) -> Any:
        kind = req["kind"]
        if kind == "put":
            return self._apply_put(ms, req)
        if kind == "delete_range":
            return self._apply_delete(ms, req)
        if kind == "txn":
            return self._apply_txn(ms, req)
        if kind == "compact":
            ms.store.kv.compact(req["rev"])
            return req["rev"]
        if kind == "lease_grant":
            l = ms.lessor.grant(req["id"], req["ttl"])
            return (l.id, l.ttl)
        if kind == "lease_revoke":
            keys = ms.lessor.revoke(req["id"])
            txn = ms.store.kv.write_txn()
            for k in keys:
                txn.delete_range(k)
            txn.end()
            ms.store.notify(txn.events)
            return len(keys)
        if kind == "lease_checkpoint":
            for lid, rem in req["checkpoints"]:
                ms.lessor.apply_checkpoint(lid, rem)
            return True
        if kind == "alarm":
            if req["action"] == "activate":
                ms.alarms.add(req["alarm"])
            else:
                ms.alarms.discard(req["alarm"])
            return sorted(ms.alarms)
        if kind == "cluster_version_set":
            # ClusterVersionSetRequest apply (membership SetVersion):
            # every member adopts the leader-decided version
            ms.cluster_version = cluster_version_str(req["ver"])
            return ms.cluster_version
        if kind == "downgrade_info_set":
            # DowngradeInfoSetRequest apply (SetDowngradeInfo)
            ms.downgrade = DowngradeInfo(
                req.get("ver", ""), bool(req["enabled"])
            )
            return ms.downgrade.enabled
        if kind == "v2":
            return self._apply_v2(ms, req)
        if kind.startswith("auth_"):
            return self._apply_auth(ms, kind, req)
        raise ServerError(f"unknown request kind {kind}")

    def _apply_v2(self, ms: MemberState, req: dict):
        """applyV2Request (apply_v2.go:124-148): interpret a committed
        RequestV2 as a v2store call. TTLs arrive as absolute expirations
        stamped at propose time (RequestV2.Expiration) so every member's
        tree — including its TTL heap — is bit-identical."""
        st = ms.v2store
        method = req["method"]
        if method == "SYNC":  # pathless: just an expiry cutoff
            st.delete_expired_keys(req["time"])
            return None
        path = req["path"]
        exp = req.get("expiration")
        refresh = bool(req.get("refresh"))
        if method == "POST":
            return st.create(path, req.get("dir", False),
                             req.get("val", ""), unique=True,
                             expire_time=exp)
        if method == "PUT":
            pv, pi = req.get("prev_value", ""), req.get("prev_index", 0)
            pe = req.get("prev_exist")
            if pe is not None:
                if pe:
                    if pi == 0 and pv == "":
                        return st.update(path, req.get("val", ""),
                                         expire_time=exp, refresh=refresh)
                    return st.compare_and_swap(path, pv, pi,
                                              req.get("val", ""),
                                              expire_time=exp,
                                              refresh=refresh)
                return st.create(path, req.get("dir", False),
                                 req.get("val", ""), unique=False,
                                 expire_time=exp)
            if pi > 0 or pv != "":
                return st.compare_and_swap(path, pv, pi,
                                          req.get("val", ""),
                                          expire_time=exp, refresh=refresh)
            return st.set(path, req.get("dir", False), req.get("val", ""),
                          expire_time=exp, refresh=refresh)
        if method == "DELETE":
            pv, pi = req.get("prev_value", ""), req.get("prev_index", 0)
            if pi > 0 or pv != "":
                return st.compare_and_delete(path, pv, pi)
            return st.delete(path, req.get("dir", False),
                             req.get("recursive", False))
        if method == "QGET":
            return st.get(path, req.get("recursive", False),
                          req.get("sorted", False))
        raise ServerError(f"unknown v2 method {method}")

    def _check_quota(self, ms: MemberState) -> None:
        if "NOSPACE" in ms.alarms:
            raise ErrNoSpace()

    def _apply_put(self, ms: MemberState, req: dict):
        self._check_quota(ms)
        txn = ms.store.kv.write_txn()
        prev = None
        if req.get("prev_kv"):
            kvs, _, _ = ms.store.kv.range(req["key"])
            prev = kvs[0] if kvs else None
        lease = req.get("lease", 0)
        if lease:
            ms.lessor.attach(lease, req["key"])
        else:
            ms.lessor.detach(req["key"])
        rev = txn.put(req["key"], req["value"], lease)
        txn.end()
        ms.store.notify(txn.events)
        return {"rev": rev, "prev_kv": prev}

    def _apply_delete(self, ms: MemberState, req: dict):
        txn = ms.store.kv.write_txn()
        prev = []
        if req.get("prev_kv"):
            prev, _, _ = ms.store.kv.range(req["key"], req.get("range_end"))
        n = txn.delete_range(req["key"], req.get("range_end"))
        rev = txn.end()
        ms.store.notify(txn.events)
        for ev in txn.events:
            ms.lessor.detach(ev[1].key)
        return {"deleted": n, "rev": rev, "prev_kvs": prev}

    def _eval_compare(self, ms: MemberState, cmp: Compare) -> bool:
        kvs, _, _ = ms.store.kv.range(cmp.key)
        kv = kvs[0] if kvs else None
        if cmp.target == "value":
            actual = kv.value if kv else b""
        elif cmp.target == "version":
            actual = kv.version if kv else 0
        elif cmp.target == "create":
            actual = kv.create_revision if kv else 0
        elif cmp.target == "mod":
            actual = kv.mod_revision if kv else 0
        elif cmp.target == "lease":
            actual = kv.lease if kv else 0
        else:
            raise ServerError(f"bad compare target {cmp.target}")
        if cmp.result == "=":
            return actual == cmp.value
        if cmp.result == "!=":
            return actual != cmp.value
        if cmp.result == ">":
            return actual > cmp.value
        if cmp.result == "<":
            return actual < cmp.value
        raise ServerError(f"bad compare result {cmp.result}")

    def _apply_txn(self, ms: MemberState, req: dict):
        self._check_quota(ms)
        succeeded = all(self._eval_compare(ms, c) for c in req["compare"])
        ops: list[Op] = req["success"] if succeeded else req["failure"]
        txn = ms.store.kv.write_txn()
        results = []
        for op in ops:
            if op.type == "put":
                # lease bookkeeping identical to the standalone put path
                if op.lease:
                    ms.lessor.attach(op.lease, op.key)
                else:
                    ms.lessor.detach(op.key)
                results.append(("put", txn.put(op.key, op.value, op.lease)))
            elif op.type == "delete":
                n_before = len(txn.events)
                results.append(("delete", txn.delete_range(op.key, op.range_end)))
                for ev in txn.events[n_before:]:
                    ms.lessor.detach(ev[1].key)
            elif op.type == "range":
                if op.rev:
                    kvs, cnt, rv = ms.store.kv.range(
                        op.key, op.range_end, op.rev, op.limit, op.count_only
                    )
                else:
                    # mid-txn reads observe this txn's earlier writes
                    kvs, cnt, rv = txn.range(
                        op.key, op.range_end, op.limit, op.count_only
                    )
                results.append(("range", kvs, cnt))
            else:
                raise ServerError(f"bad txn op {op.type}")
        rev = txn.end()
        ms.store.notify(txn.events)
        return {"succeeded": succeeded, "responses": results, "rev": rev}

    def _apply_auth(self, ms: MemberState, kind: str, req: dict):
        a = ms.auth
        fn = {
            "auth_enable": lambda: a.auth_enable(),
            "auth_disable": lambda: a.auth_disable(),
            "auth_user_add": lambda: a.user_add(
                req["name"], no_password=req.get("no_password", False),
                salt=req.get("salt"), pw_hash=req.get("pw_hash"),
            ),
            "auth_user_delete": lambda: a.user_delete(req["name"]),
            "auth_user_change_password": lambda: a.user_change_password(
                req["name"], salt=req.get("salt"), pw_hash=req.get("pw_hash")
            ),
            "auth_user_grant_role": lambda: a.user_grant_role(
                req["name"], req["role"]
            ),
            "auth_user_revoke_role": lambda: a.user_revoke_role(
                req["name"], req["role"]
            ),
            "auth_role_add": lambda: a.role_add(req["name"]),
            "auth_role_delete": lambda: a.role_delete(req["name"]),
            "auth_role_grant_permission": lambda: a.role_grant_permission(
                req["role"], req["perm"]
            ),
            "auth_role_revoke_permission": lambda: a.role_revoke_permission(
                req["role"], req["key"], req.get("range_end")
            ),
        }.get(kind)
        if fn is None:
            raise ServerError(f"unknown auth request {kind}")
        fn()
        return True

    # ------------------------------------------------------- request routing
    # log-if-slower-than threshold for request traces (the
    # warningApplyDuration dump rule, v3_server.go:602-610), seconds
    TRACE_THRESHOLD_S = 0.5
    # per-entry apply threshold feeding etcd_server_slow_apply_total
    # (applyTook > warningApplyDuration, etcdserver/server.go)
    SLOW_APPLY_THRESHOLD_S = 0.1
    # read-index wait threshold feeding
    # etcd_server_slow_read_indexes_total (slowReadIndex,
    # v3_server.go linearizableReadLoop)
    SLOW_READ_INDEX_THRESHOLD_S = 0.5
    # how many completed request spans to keep for to_chrome_trace
    REQ_SPAN_CAP = 256

    def _record_span(self, trace) -> None:
        """Retire a finished Trace into the bounded span buffer that
        blackbox.to_chrome_trace exports (host-request tracks)."""
        if trace is None or trace.is_empty:
            return
        self.req_spans.append(trace.to_span())
        if len(self.req_spans) > self.REQ_SPAN_CAP:
            del self.req_spans[: len(self.req_spans) - self.REQ_SPAN_CAP]

    def _propose(self, req: dict, member: int | None = None,
                 trace=None) -> Any:
        """processInternalRaftRequestOnce (v3_server.go:643-704)."""
        from etcd_tpu.utils.trace import Field, Trace

        if trace is None or trace.is_empty:
            trace = Trace(req.get("kind", "?"), Field("member", member))
        else:
            trace.add_field(Field("member", member))
        lead = self.ensure_leader()
        at = member if member is not None else lead
        # backpressure: commit-apply gap (v3_server.go:644-648)
        s = self.cl.s
        gap = int(np.asarray(s.commit[at, ..., self.c])) - self.members[at].applied_index
        if gap > self.MAX_GAP:
            raise ErrTooManyRequests()
        word = self._next_word
        self._next_word += 1
        req["_serve_m"] = at
        self.requests[word] = req
        self.cl.propose(at, word, c=self.c)
        trace.step("proposed through raft", Field("word", word))
        serving = self.members[at]
        try:
            for _ in range(self.MAX_APPLY_WAIT_ROUNDS):
                self.step()
                if word in serving.results:
                    trace.step("applied; result ready")
                    res = serving.results.pop(word)
                    if isinstance(res, Exception):
                        raise res
                    if self.durable_proposes:
                        self.commit_backends()
                        trace.step("backends fsynced")
                    return res
            raise ErrTimeout(req["kind"])
        finally:
            trace.log_if_long(self.TRACE_THRESHOLD_S)
            self._record_span(trace)

    def _header(self, m: int) -> ResponseHeader:
        s = self.cl.s
        return ResponseHeader(
            cluster_id=self.c,
            member_id=m,
            revision=self.members[m].store.kv.current_rev,
            raft_term=int(np.asarray(s.term[m, ..., self.c])),
        )

    # ------------------------------------------------------------- public KV
    def put(self, key: bytes, value: bytes, lease: int = 0,
            prev_kv: bool = False, token: str | None = None, trace=None):
        self._authz(token, key, None, write=True)
        res = self._propose(
            {"kind": "put", "key": key, "value": value, "lease": lease,
             "prev_kv": prev_kv}, trace=trace
        )
        self._maybe_raise_nospace()
        return res

    def delete_range(self, key: bytes, range_end: bytes | None = None,
                     prev_kv: bool = False, token: str | None = None,
                     trace=None):
        self._authz(token, key, range_end, write=True)
        return self._propose(
            {"kind": "delete_range", "key": key, "range_end": range_end,
             "prev_kv": prev_kv}, trace=trace
        )

    def txn(self, compare: list[Compare], success: list[Op],
            failure: list[Op] | None = None, token: str | None = None,
            trace=None):
        for cmp_ in compare:
            self._authz(token, cmp_.key, None, write=False)
        for op in success + (failure or []):
            self._authz(token, op.key, op.range_end, write=op.type != "range")
        return self._propose(
            {"kind": "txn", "compare": compare, "success": success,
             "failure": failure or []}, trace=trace
        )

    def range(self, key: bytes, range_end: bytes | None = None, rev: int = 0,
              limit: int = 0, serializable: bool = False, member: int | None = None,
              count_only: bool = False, token: str | None = None, trace=None):
        """Range: linearizable by default via ReadIndex barrier
        (v3_server.go:95-133,709)."""
        from etcd_tpu.utils.trace import Field, Trace

        if trace is None or trace.is_empty:
            trace = Trace("range", Field("serializable", serializable))
        else:
            trace.add_field(Field("serializable", serializable))
        self._authz(token, key, range_end, write=False)
        m = member if member is not None else self.ensure_leader()
        if not serializable:
            self.linearizable_read_notify(m, trace=trace)
            trace.step("read index confirmed; applied caught up")
        kvs, count, used = self.members[m].store.kv.range(
            key, range_end, rev, limit, count_only
        )
        trace.step("range keys from mvcc", Field("count", count))
        trace.log_if_long(self.TRACE_THRESHOLD_S)
        self._record_span(trace)
        return {"kvs": kvs, "count": count, "rev": used,
                "header": self._header(m)}

    def compact(self, rev: int):
        return self._propose({"kind": "compact", "rev": rev})

    def linearizable_read_notify(self, member: int, trace=None) -> None:
        """linearizableReadLoop round (v3_server.go:709-879): ReadIndex, then
        wait until applied >= read index. A wait past
        SLOW_READ_INDEX_THRESHOLD_S (or a timeout) counts into
        etcd_server_slow_read_indexes_total, the reference's slowReadIndex
        signal."""
        t0 = time.perf_counter()

        def _settle(ok: bool) -> None:
            dt = time.perf_counter() - t0
            if not ok or dt > self.SLOW_READ_INDEX_THRESHOLD_S:
                self.slow_read_index_total += 1
                from etcd_tpu.utils.logging import get_logger

                get_logger().warning(
                    "slow read index: member=%d waited %.3fs (%s)",
                    member, dt, "confirmed" if ok else "timed out")

        self.ensure_leader()
        ctx = self.cl.read_index(member, c=self.c)
        if trace is not None:
            trace.step("read index requested")
        for _ in range(self.MAX_APPLY_WAIT_ROUNDS):
            self.step()
            rs_ctx = np.asarray(self.cl.s.rs_ctx[member, ..., self.c])
            rs_idx = np.asarray(self.cl.s.rs_index[member, ..., self.c])
            hits = np.nonzero(rs_ctx == ctx)[0]
            if hits.size:
                need = int(rs_idx[hits[0]])
                # consume the ReadStates queue (the app drains rd.ReadStates
                # every Ready, etcdserver/raft.go:192-200; leaving them would
                # fill the R-slot device ring and drop later reads)
                self.cl.set_node(
                    member, c=self.c,
                    rs_ctx=np.zeros_like(rs_ctx),
                    rs_index=np.zeros_like(rs_idx),
                    rs_count=0,
                )
                while self.members[member].applied_index < need:
                    self.step()
                _settle(True)
                return
        _settle(False)
        raise ErrTimeout("read index")

    # ---------------------------------------------------------------- leases
    def lease_grant(self, lease_id: int, ttl: int):
        lid, granted = self._propose(
            {"kind": "lease_grant", "id": lease_id, "ttl": ttl}
        )
        return {"id": lid, "ttl": granted}

    def lease_revoke(self, lease_id: int):
        return self._propose({"kind": "lease_revoke", "id": lease_id})

    def lease_keepalive(self, lease_id: int):
        """Primary lessor renews directly (leasehttp fronted in the ref);
        replicate a checkpoint so followers learn the new remaining TTL."""
        lead = self.ensure_leader()
        ttl = self.members[lead].lessor.renew(lease_id)
        self._propose(
            {"kind": "lease_checkpoint",
             "checkpoints": [(lease_id, ttl)]}
        )
        return {"id": lease_id, "ttl": ttl}

    def lease_time_to_live(self, lease_id: int):
        lead = self.ensure_leader()
        ttl, keys = self.members[lead].lessor.time_to_live(lease_id)
        return {"id": lease_id, "ttl": ttl, "keys": keys}

    def leases(self):
        lead = self.ensure_leader()
        return sorted(self.members[lead].lessor.leases)

    def _expire_leases(self) -> None:
        """Leader lessor's due leases become LeaseRevoke proposals
        (lessor.go runLoop -> server revoke)."""
        lead = self.leader()
        if lead == NONE_ID:
            return
        lessor = self.members[lead].lessor
        if not lessor.primary:
            # promotion follows raft leadership (lessor.go Promote)
            lessor.promote(extend=self.cl.cfg.election_tick)
            for m, ms in enumerate(self.members):
                if m != lead and ms.lessor.primary:
                    ms.lessor.demote()
        due = lessor.expired()
        for i, lid in enumerate(due):
            try:
                self._propose({"kind": "lease_revoke", "id": lid})
            except ErrLeaseNotFound:
                # the revoke raced an earlier one (double expiry across
                # ticks, or the previous leader's queued revoke landed
                # first): already gone is SUCCESS for the expiry loop,
                # like the reference's expired-lease retry loop treating
                # ErrLeaseNotFound as completed (etcdserver/server.go
                # revokeExpiredLeases)
                continue
            except ServerError:
                # retry this id and the rest next tick; their heap entries
                # were popped by expired()
                lessor.defer_expiry(due[i:])
                return

    # ----------------------------------------------------------------- watch
    def watch(self, member: int, key: bytes, range_end: bytes | None = None,
              start_rev: int = 0, prev_kv: bool = False,
              fragment: bool = False, progress_notify: bool = False,
              filters: tuple = ()):
        return self.members[member].store.watch(
            key, range_end, start_rev, prev_kv,
            fragment=fragment, progress_notify=progress_notify,
            filters=filters,
        )

    def watch_events(self, member: int, watch_id: int,
                     limit: int | None = None):
        self.members[member].store.sync_watchers()
        return self.members[member].store.take_events(watch_id, limit)

    def watch_pending(self, member: int, watch_id: int) -> int:
        return self.members[member].store.pending_events(watch_id)

    def watch_progress(self, member: int, watch_id: int | None = None):
        """WatchProgressRequest analog. Per-watcher (watch_id given):
        current revision only if that watcher is synced and drained, else
        None (mvcc watchStream.RequestProgress). Stream-level
        (watch_id=None): the bare current revision unconditionally — the
        reference's ProgressRequest path sends newResponseHeader(Rev())
        with WatchId -1 without any sync check (api/v3rpc/watch.go:339-345)
        and leaves interpretation to the client."""
        store = self.members[member].store
        if watch_id is not None:
            return store.progress(watch_id)
        return store.kv.current_rev

    def cancel_watch(self, member: int, watch_id: int) -> bool:
        return self.members[member].store.cancel(watch_id)

    # ------------------------------------------------------------ membership
    # ------------------------------------------------------------ v2 API
    # the v2 request front (v2_server.go): every mutation — and QGET, the
    # quorum read — is ordered through consensus; plain gets are served
    # from the serving member's applied tree (the v2 "serializable" read)

    def v2_request(self, method: str, path: str, *, val: str = "",
                   dir: bool = False, prev_value: str = "",
                   prev_index: int = 0, prev_exist: bool | None = None,
                   recursive: bool = False, sorted_: bool = False,
                   refresh: bool = False, ttl: int | None = None,
                   member: int | None = None):
        req: dict[str, Any] = {
            "kind": "v2", "method": method, "path": path, "val": val,
            "dir": dir, "prev_value": prev_value,
            "prev_index": prev_index, "prev_exist": prev_exist,
            "recursive": recursive, "sorted": sorted_, "refresh": refresh,
        }
        if ttl is not None:
            # RequestV2.Expiration: absolute, stamped at propose time so
            # the apply is identical on every member (client.go:496-523)
            req["expiration"] = self.v2_now() + ttl
        return self._propose(req, member=member)

    def v2_get(self, path: str, recursive: bool = False,
               sorted_: bool = False, member: int | None = None):
        m = member if member is not None else self.ensure_leader()
        return self.members[m].v2store.get(path, recursive, sorted_)

    def v2_sync(self, member: int | None = None) -> None:
        """The SYNC proposal (etcdserver sync): the serving member's
        clock decides the expiry cutoff, consensus orders it, every
        member expires the same keys."""
        self._propose({"kind": "v2", "method": "SYNC",
                       "time": self.v2_now()}, member=member)

    def v2_watch(self, path: str, recursive: bool = False,
                 stream: bool = False, since_index: int = 0,
                 member: int | None = None):
        m = member if member is not None else self.ensure_leader()
        return self.members[m].v2store.watch(path, recursive, stream,
                                             since_index)

    def v2_stats(self, member: int | None = None) -> dict:
        m = member if member is not None else self.ensure_leader()
        return self.members[m].v2store.json_stats()

    def member_config(self) -> HostConfig:
        """Current config from the leader's applied masks."""
        s = self.cl.s
        lead = self.ensure_leader()
        cfg = HostConfig()
        v = np.asarray(s.voters[lead, ..., self.c])
        vo = np.asarray(s.voters_out[lead, ..., self.c])
        l = np.asarray(s.learners[lead, ..., self.c])
        ln = np.asarray(s.learners_next[lead, ..., self.c])
        cfg.voters = {i for i in range(self.M) if v[i]}
        cfg.voters_outgoing = {i for i in range(self.M) if vo[i]}
        cfg.learners = {i for i in range(self.M) if l[i]}
        cfg.learners_next = {i for i in range(self.M) if ln[i]}
        cfg.auto_leave = bool(np.asarray(s.auto_leave[lead, ..., self.c]))
        cfg.progress = cfg.voters | cfg.voters_outgoing | cfg.learners
        cfg.progress_learner = set(cfg.learners)
        return cfg

    def _conf_change(self, ccs, validate) -> None:
        """mayAddMember-style guard (server.go:1293) then propose the
        encoded change and wait for it to apply on the leader."""
        lead = self.ensure_leader()
        validate(Changer(self.member_config()))  # raises ConfChangeError
        word = ccdev.encode(ccs)
        before = self.member_config()
        self.cl.propose_conf_change(lead, word, c=self.c)
        self.stabilize()
        self.stabilize()

    def member_add(self, member_id: int, learner: bool = False):
        from etcd_tpu.types import CC_ADD_LEARNER, CC_ADD_NODE

        cfg = self.member_config()
        if member_id in cfg.progress:
            # membership.ErrIDExists (api/membership/cluster.go AddMember)
            raise ServerError(f"member {member_id} already exists")
        op = CC_ADD_LEARNER if learner else CC_ADD_NODE
        self._conf_change(
            [(op, member_id)],
            lambda ch: ch.simple([(op, member_id)]),
        )

    def member_remove(self, member_id: int):
        from etcd_tpu.types import CC_REMOVE_NODE

        cfg = self.member_config()
        if member_id not in cfg.progress:
            # membership.ErrIDRemoved/NotFound (RemoveMember guards)
            raise ServerError(f"member {member_id} not found")
        # strict-reconfig-check analog (mayRemoveMember, server.go:1293):
        # refuse a removal that would leave no quorum of started members
        if member_id in cfg.voters and len(cfg.voters) - 1 < 1:
            raise ServerError("removing last voter would break the cluster")
        self._conf_change(
            [(CC_REMOVE_NODE, member_id)],
            lambda ch: ch.simple([(CC_REMOVE_NODE, member_id)]),
        )

    def member_promote(self, member_id: int):
        """PromoteMember with the readiness guard (server.go:1341,1445:
        learner must be within 90% of the leader's last index)."""
        from etcd_tpu.types import CC_ADD_NODE

        lead = self.ensure_leader()
        s = self.cl.s
        match = int(np.asarray(s.match[lead, member_id, ..., self.c]))
        last = int(np.asarray(s.last_index[lead, ..., self.c]))
        if last > 0 and match < last * 9 // 10:
            raise ServerError("learner is not ready to be promoted")
        self._conf_change(
            [(CC_ADD_NODE, member_id)],
            lambda ch: ch.simple([(CC_ADD_NODE, member_id)]),
        )

    # ------------------------------------------------------------------ auth
    def _authz(self, token, key, range_end, write):
        lead = self.leader()
        if lead == NONE_ID:
            return
        a = self.members[lead].auth
        if not a.enabled:
            return
        if token is None:
            raise ServerError("auth token required")
        a.check(token, key, range_end, write)

    def auth_request(self, kind: str, **kw):
        # Hash passwords once at propose time and replicate (salt, hash) in
        # the entry, like auth/store.go replicating the bcrypt hash inside
        # AuthUserAdd — apply stays deterministic across members and replays.
        if kind in ("auth_user_add", "auth_user_change_password"):
            import os as _os

            from etcd_tpu.server.auth import _hash

            if not kw.get("no_password"):
                salt = _os.urandom(16)
                kw["salt"] = salt
                kw["pw_hash"] = _hash(kw.pop("password", ""), salt)
            else:
                # no_password users still need a deterministic (empty) salt,
                # or each member would roll its own urandom at apply time
                kw.pop("password", None)
                kw["salt"] = b""
                kw["pw_hash"] = b""
        return self._propose({"kind": kind, **kw})

    def authenticate(self, name: str, password: str) -> str:
        lead = self.ensure_leader()
        return self.members[lead].auth.authenticate(name, password)

    # ----------------------------------------------------------- maintenance
    # -- cluster version negotiation + downgrade (monitorVersions /
    # monitorDowngrade, server.go:2160-2280; Downgrade RPC,
    # v3_server.go:901-990) ------------------------------------------------
    def set_server_version(self, m: int, version: str) -> None:
        """Swap member m's binary version (mixed-version fleets / rolling
        up-/downgrades). Recorded in the override map so a later
        restart-from-disk boots the same \"binary\"."""
        self.server_versions[m] = version
        self.members[m].server_version = version

    def member_versions(self) -> dict[int, dict | None]:
        """Per-member {server, cluster} versions; None for unreachable
        (crashed) members — the cluster_util.go getVersions analog, read
        in-process instead of over peer HTTP."""
        return {
            m: (
                None
                if ms.crashed
                else {
                    "server": ms.server_version,
                    "cluster": ms.cluster_version or MIN_CLUSTER_VERSION,
                }
            )
            for m, ms in enumerate(self.members)
        }

    def cluster_version(self, member: int | None = None) -> str | None:
        """A member's applied view of the negotiated cluster version
        (EtcdServer.ClusterVersion)."""
        if member is None:
            member = self.leader()
            if member == NONE_ID or member < 0:
                member = 0
        return self.members[member].cluster_version

    def _version_monitor(self, lead: int) -> VersionMonitor:
        ec = self

        class _Adapter:
            def get_cluster_version(self):
                return ec.members[lead].cluster_version

            def get_downgrade_info(self):
                return ec.members[lead].downgrade

            def get_versions(self):
                return ec.member_versions()

            def update_cluster_version(self, ver: str):
                ec._propose(
                    {"kind": "cluster_version_set", "ver": ver}, member=lead
                )

            def downgrade_cancel(self):
                ec._propose(
                    {"kind": "downgrade_info_set", "enabled": False},
                    member=lead,
                )

        return VersionMonitor(_Adapter())

    def monitor_versions(self) -> str | None:
        """One leader monitor pass: decide min member version, propose a
        cluster-version bump through consensus when the change is valid.
        Returns the proposed version, or None. The embed tick loop calls
        this on the monitorVersionInterval; tests call it directly."""
        lead = self.leader()
        if lead == NONE_ID or lead < 0 or self.members[lead].crashed:
            return None
        return self._version_monitor(lead).update_cluster_version_if_needed()

    def monitor_downgrade(self) -> bool:
        """Cancel the live downgrade job once every member's cluster
        version reached the target (monitorDowngrade)."""
        lead = self.leader()
        if lead == NONE_ID or lead < 0 or self.members[lead].crashed:
            return False
        return self._version_monitor(lead).cancel_downgrade_if_needed()

    def downgrade(self, action: str, version: str | None = None,
                  member: int | None = None) -> dict:
        """Downgrade VALIDATE/ENABLE/CANCEL (v3_server.go:901-990)."""
        at = member if member is not None else self.ensure_leader()
        if action == "validate":
            self.linearizable_read_notify(at)
            cv = self.members[at].cluster_version
            if cv is None:
                raise ErrClusterVersionUnavailable()
            try:
                want = major_minor(version or "")
            except ValueError:
                raise ErrInvalidDowngradeTargetVersion()
            if want != major_minor(allowed_downgrade_version(cv)):
                raise ErrInvalidDowngradeTargetVersion()
            if self.members[at].downgrade.enabled:
                raise ErrDowngradeInProcess()
            return {"version": cv}
        if action == "enable":
            res = self.downgrade("validate", version, member=at)
            target = cluster_version_str(version or "")
            self._propose(
                {"kind": "downgrade_info_set", "enabled": True,
                 "ver": target},
                member=at,
            )
            # the version monitor will now lower the cluster version to
            # the target (is_valid_version_change accepts the one-minor
            # downgrade) as its next pass
            return {"version": res["version"]}
        if action == "cancel":
            self.linearizable_read_notify(at)
            if not self.members[at].downgrade.enabled:
                raise ErrNoInflightDowngrade()
            self._propose(
                {"kind": "downgrade_info_set", "enabled": False}, member=at
            )
            return {"version": self.members[at].cluster_version}
        raise ServerError(f"unknown downgrade action {action}")

    def status(self, member: int) -> dict:
        s = self.cl.s
        ms = self.members[member]
        return {
            "leader": self.leader(),
            "raft_term": int(np.asarray(s.term[member, ..., self.c])),
            "raft_index": int(np.asarray(s.last_index[member, ..., self.c])),
            "raft_applied_index": ms.applied_index,
            "db_size": ms.store.kv.size,
            "is_learner": bool(np.asarray(s.learners[member, member, ..., self.c])),
            "alarms": sorted(ms.alarms),
            "version": ms.server_version,
            "cluster_version": ms.cluster_version,
            "downgrade": ms.downgrade.to_dict(),
        }

    def hash_kv(self, member: int, rev: int = 0) -> int:
        return self.members[member].store.kv.hash_kv(rev)

    def corruption_check(self) -> None:
        """Cross-member KV-hash comparison at a common revision
        (etcdserver/corrupt.go): members at the same applied index must have
        identical hashes."""
        by_applied: dict[int, set[int]] = {}
        for m, ms in enumerate(self.members):
            by_applied.setdefault(ms.applied_index, set()).add(
                ms.store.kv.hash_kv()
            )
        for applied, hashes in by_applied.items():
            if len(hashes) > 1:
                raise ErrCorrupt(f"applied={applied} hashes={hashes}")

    def alarm(self, action: str, alarm: str):
        return self._propose({"kind": "alarm", "action": action, "alarm": alarm})

    def _maybe_raise_nospace(self) -> None:
        if not self.quota_bytes:
            return
        lead = self.leader()
        if lead == NONE_ID:
            return
        ms = self.members[lead]
        if ms.store.kv.size > self.quota_bytes and "NOSPACE" not in ms.alarms:
            from etcd_tpu.utils.logging import get_logger

            get_logger().warning(
                "quota exceeded (%d > %d bytes); raising NOSPACE alarm",
                ms.store.kv.size, self.quota_bytes,
            )
            self.alarm("activate", "NOSPACE")

    def snapshot(self, member: int) -> dict:
        """Maintenance.Snapshot analog: serialize the member's applied KV."""
        ms = self.members[member]
        return {
            "applied_index": ms.applied_index,
            "kv": ms.store.kv.to_snapshot(),
        }
