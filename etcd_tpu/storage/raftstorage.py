"""Pluggable Storage contract + MemoryStorage.

This is the host-side half of the seam the reference defines at
raft/storage.go:46-72 — the ``Storage`` interface (InitialState / Entries /
Term / LastIndex / FirstIndex / Snapshot) with its error taxonomy
(ErrCompacted / ErrUnavailable / ErrSnapshotTemporarilyUnavailable,
raft/storage.go:24-38) — plus the universal fake, ``MemoryStorage``
(raft/storage.go:76-273), which every reference test tier drives.

Design differences from the reference (deliberate, TPU-first):
  * Entries are fixed-width integer records (index, term, type, data word),
    matching the device log ring (etcd_tpu/models/state.py log_term/
    log_data/log_type); arbitrary byte payloads live in a host-side intern
    table (:class:`PayloadTable`), the same payload-ref discipline the
    server layer uses. ``MaxSizePerMsg``-style limits therefore count
    entries, not bytes.
  * Member ids are 0-based; NONE_ID is -1 (see etcd_tpu/types.py).
  * No mutex: the engine is single-threaded per group by construction
    (lockstep rounds), so MemoryStorage needs no locking discipline.
"""
from __future__ import annotations

import dataclasses

from etcd_tpu.types import ENTRY_NORMAL, NONE_ID


class ErrCompacted(Exception):
    """Requested index predates the last snapshot (raft/storage.go:27)."""


class ErrSnapOutOfDate(Exception):
    """Snapshot request older than the existing one (raft/storage.go:30)."""


class ErrUnavailable(Exception):
    """Requested entry is not yet available (raft/storage.go:33)."""


class ErrSnapshotTemporarilyUnavailable(Exception):
    """Snapshot is being prepared; retry later (raft/storage.go:36)."""


@dataclasses.dataclass(frozen=True)
class Entry:
    """One log entry record (raftpb.Entry analog, raft.proto:69-79)."""

    index: int
    term: int
    type: int = ENTRY_NORMAL
    data: int = 0  # payload word (PayloadTable ref or conf-change word)


@dataclasses.dataclass
class HardState:
    """raftpb.HardState (raft.proto:102-106)."""

    term: int = 0
    vote: int = NONE_ID
    commit: int = 0

    def is_empty(self) -> bool:
        return self == HardState()


@dataclasses.dataclass
class ConfState:
    """raftpb.ConfState (raft.proto:115-130) as 0-based id lists."""

    voters: tuple[int, ...] = ()
    voters_outgoing: tuple[int, ...] = ()
    learners: tuple[int, ...] = ()
    learners_next: tuple[int, ...] = ()
    auto_leave: bool = False

    @staticmethod
    def from_masks(voters, voters_out, learners, learners_next, auto_leave):
        ids = lambda m: tuple(int(i) for i in range(len(m)) if m[i])
        return ConfState(
            ids(voters), ids(voters_out), ids(learners), ids(learners_next),
            bool(auto_leave),
        )

    def masks(self, m: int):
        import numpy as np

        def mk(ids):
            a = np.zeros((m,), bool)
            for i in ids:
                a[i] = True
            return a

        return (
            mk(self.voters), mk(self.voters_outgoing), mk(self.learners),
            mk(self.learners_next),
        )


@dataclasses.dataclass
class SnapshotMeta:
    index: int = 0
    term: int = 0
    conf_state: ConfState = dataclasses.field(default_factory=ConfState)
    app_hash: int = 0  # applied-state hash at `index` (KV_HASH analog)


@dataclasses.dataclass
class Snapshot:
    meta: SnapshotMeta = dataclasses.field(default_factory=SnapshotMeta)
    data: tuple[int, ...] = ()  # applied payload words (appender history)

    def is_empty(self) -> bool:
        return self.meta.index == 0


class Storage:
    """The pluggable persistence contract (raft/storage.go:46-72).

    Implementations: :class:`MemoryStorage` below (host lists) and
    ``DeviceLaneStorage`` (etcd_tpu/models/rawnode.py), which reads one
    lane of the device fleet.
    """

    def initial_state(self) -> tuple[HardState, ConfState]:
        raise NotImplementedError

    def entries(self, lo: int, hi: int, max_entries: int | None = None) -> list[Entry]:
        """Entries [lo, hi). Raises ErrCompacted / ErrUnavailable."""
        raise NotImplementedError

    def term(self, i: int) -> int:
        raise NotImplementedError

    def first_index(self) -> int:
        raise NotImplementedError

    def last_index(self) -> int:
        raise NotImplementedError

    def snapshot(self) -> Snapshot:
        raise NotImplementedError


class MemoryStorage(Storage):
    """In-memory Storage (raft/storage.go:76-273), list-backed.

    The reference marks the log truncation point with a dummy zeroth
    entry, kept *separate* from the retained snapshot (Compact moves only
    the dummy; CreateSnapshot replaces only the snapshot). Here the dummy
    is the explicit ``(_offset, _offset_term)`` pair: ``ents`` holds
    exactly (_offset, last_index].
    """

    def __init__(self):
        self.hard_state = HardState()
        self.snap = Snapshot()
        self.ents: list[Entry] = []
        self._offset = 0
        self._offset_term = 0

    # -- Storage interface ---------------------------------------------------
    def initial_state(self):
        return self.hard_state, self.snap.meta.conf_state

    def first_index(self) -> int:
        return self._offset + 1

    def last_index(self) -> int:
        return self._offset + len(self.ents)

    def entries(self, lo, hi, max_entries=None):
        if lo <= self._offset:
            raise ErrCompacted(lo)
        if hi > self.last_index() + 1:
            raise ErrUnavailable(hi)
        out = self.ents[lo - self._offset - 1 : hi - self._offset - 1]
        if max_entries is not None:
            out = out[:max_entries]
        return list(out)

    def term(self, i) -> int:
        if i < self._offset:
            raise ErrCompacted(i)
        if i == self._offset:
            return self._offset_term
        if i > self.last_index():
            raise ErrUnavailable(i)
        return self.ents[i - self._offset - 1].term

    def snapshot(self) -> Snapshot:
        return self.snap

    # -- mutators (raft/storage.go:170-273) ----------------------------------
    def set_hard_state(self, hs: HardState) -> None:
        self.hard_state = dataclasses.replace(hs)

    def apply_snapshot(self, snap: Snapshot) -> None:
        if snap.meta.index <= self.snap.meta.index:
            raise ErrSnapOutOfDate(snap.meta.index)
        self.snap = snap
        self.ents = []
        self._offset = snap.meta.index
        self._offset_term = snap.meta.term

    def create_snapshot(self, i: int, cs: ConfState | None, data=(),
                        app_hash: int = 0) -> Snapshot:
        """Make (and retain) a snapshot at applied index i
        (raft/storage.go:180-205). Does NOT move first_index."""
        if i <= self.snap.meta.index:
            raise ErrSnapOutOfDate(i)
        if i > self.last_index():
            raise ErrUnavailable(i)
        cs = cs if cs is not None else self.snap.meta.conf_state
        self.snap = Snapshot(
            meta=SnapshotMeta(index=i, term=self.term(i), conf_state=cs,
                              app_hash=app_hash),
            data=tuple(data),
        )
        return self.snap

    def compact(self, compact_index: int) -> None:
        """Discard entries <= compact_index (raft/storage.go:208-233).
        Moves first_index; the retained snapshot is untouched."""
        if compact_index <= self._offset:
            raise ErrCompacted(compact_index)
        if compact_index > self.last_index():
            raise ErrUnavailable(compact_index)
        term = self.term(compact_index)
        self.ents = self.ents[compact_index - self._offset :]
        self._offset = compact_index
        self._offset_term = term

    def append(self, ents: list[Entry]) -> None:
        """Append with truncate-on-conflict (raft/storage.go:236-273)."""
        if not ents:
            return
        first, last = self.first_index(), ents[0].index + len(ents) - 1
        if last < first:
            return  # all compacted away
        if first > ents[0].index:
            ents = ents[first - ents[0].index :]
        pos = ents[0].index - self._offset - 1
        if pos > len(self.ents):
            raise ErrUnavailable(
                f"missing log entries [last: {self.last_index()}, "
                f"append at: {ents[0].index}]"
            )
        self.ents = self.ents[:pos] + list(ents)


def bootstrap_from_wal(wal) -> tuple["MemoryStorage", bytes]:
    """Crash–restart recovery: replay a WAL into a fresh MemoryStorage —
    the host-storage mirror of the chaos tier's on-device crash model
    (etcdserver/storage.go readWAL + raft restart path). ``wal`` is any
    object with the :meth:`etcd_tpu.storage.wal.WAL.read_all` contract;
    read_all itself repairs a torn tail, so what arrives here is exactly
    the durable prefix.

    Validates the recovery invariant the device checkers enforce per
    round: the persisted HardState's commit must be covered by the
    surviving log (WAL.save writes a batch's entries BEFORE its
    hardstate record, so a prefix tear can drop a batch's hardstate but
    never keep a hardstate whose entries it dropped). A violation means
    the WAL bytes are inconsistent in a way repair cannot have
    produced — fail loudly rather than boot a node that breaks leader
    completeness. (Snapshot-vs-tail consistency needs no check:
    apply_snapshot resets the storage window to the snapshot cursor, so
    the replayed tail can never sit behind it.)

    Returns (storage, metadata).
    """
    from etcd_tpu.storage.wal import WALError

    metadata, hs, ents, snap = wal.read_all()
    ms = MemoryStorage()
    # index 0 is the initial empty-snapshot marker some WALs open with;
    # a fresh MemoryStorage already sits at index 0 and apply_snapshot
    # would reject it as out of date
    if snap and snap["index"] > 0:
        ms.apply_snapshot(Snapshot(
            meta=SnapshotMeta(index=snap["index"], term=snap["term"]),
        ))
    if hs is not None:
        ms.set_hard_state(HardState(
            term=hs["term"], vote=hs["vote"], commit=hs["commit"],
        ))
    ms.append([
        Entry(index=e["index"], term=e["term"],
              type=e.get("type", ENTRY_NORMAL), data=e.get("data", 0))
        for e in ents
    ])
    if ms.hard_state.commit > ms.last_index():
        raise WALError(
            f"persisted commit {ms.hard_state.commit} exceeds the durable "
            f"log tail {ms.last_index()} — WAL bytes are inconsistent"
        )
    return ms, metadata


class PayloadTable:
    """Intern table mapping arbitrary payloads <-> int32 data words.

    The device log carries int32 payload refs; real bytes stay host-side —
    the same discipline the server layer's payload-ref table uses. Word 0
    is the empty payload.
    """

    def __init__(self):
        self._by_word: dict[int, bytes] = {0: b""}
        self._by_payload: dict[bytes, int] = {b"": 0}

    def intern(self, payload: bytes | str) -> int:
        if isinstance(payload, str):
            payload = payload.encode()
        w = self._by_payload.get(payload)
        if w is None:
            w = len(self._by_word)
            self._by_word[w] = payload
            self._by_payload[payload] = w
        return w

    def lookup(self, word: int) -> bytes:
        return self._by_word.get(int(word), b"")
