"""Fleet observability tests: metered round counters, fleet summary,
and per-lane BasicStatus (metrics.go / status.go analogs)."""
import jax
import jax.numpy as jnp
import numpy as np

from etcd_tpu.models.engine import empty_inbox, init_fleet
from etcd_tpu.models.metrics import (
    basic_status,
    build_metered_round,
    fleet_summary,
    metrics_report,
    zero_metrics,
)
from etcd_tpu.types import ROLE_LEADER, Spec
from etcd_tpu.utils.config import RaftConfig

SPEC = Spec(M=3, L=16, E=2, K=4, W=2, R=2, A=4)
CFG = RaftConfig(election_tick=3, heartbeat_tick=1, max_inflight=2)


def drive(C=4, faulty=False, rounds=12):
    state = init_fleet(SPEC, C, election_tick=CFG.election_tick)
    inbox = empty_inbox(SPEC, C)
    metrics = zero_metrics()
    step = jax.jit(build_metered_round(CFG, SPEC))
    M = SPEC.M
    z2 = jnp.zeros((M, C), jnp.int32)
    zp = jnp.zeros((M, SPEC.E, C), jnp.int32)
    no = jnp.zeros((M, C), jnp.bool_)
    keep = jnp.ones((M, M, C), jnp.bool_)
    if faulty:
        keep = keep.at[2, :, :].set(False).at[:, 2, :].set(False)
    hup = no.at[0].set(True)
    state, inbox, metrics = step(
        state, inbox, z2, zp, zp, z2, hup, no, keep, metrics
    )
    prop = z2.at[0].set(1)
    pdata = zp.at[0, 0].set(5)
    for _ in range(rounds - 1):
        state, inbox, metrics = step(
            state, inbox, prop, pdata, zp, z2, no, no, keep, metrics
        )
    return state, metrics


def test_metered_round_counters():
    C = 4
    state, metrics = drive(C=C)
    rep = metrics_report(metrics, elapsed_s=1.0, n_groups=C,
                         n_members=SPEC.M)
    assert rep["rounds"] == 12
    assert rep["elections_won"] == C  # one leader per group
    assert rep["leader_losses"] == 0
    assert rep["msgs_dropped"] == 0
    assert rep["msgs_delivered"] > 0
    # every group reached one-commit-per-round steady state eventually
    assert rep["commits_total"] >= C * 5
    assert rep["applies_total"] >= C * 5
    # cumulative buckets: the +inf slot counts one sample/node/round
    assert rep["commit_apply_lag_hist"]["inf"] == 12 * C * SPEC.M
    hist = rep["commit_apply_lag_hist"]
    assert hist["le_0"] <= hist["le_32"] <= hist["inf"]


def test_metered_round_counts_drops():
    state, metrics = drive(C=2, faulty=True)
    rep = metrics_report(metrics)
    assert rep["msgs_dropped"] > 0
    # the isolated node 2 never hears an append
    assert int(state.commit[2].max()) == 0


def test_fleet_summary():
    state, _ = drive(C=4)
    s = fleet_summary(state)
    assert s["groups"] == 4 and s["nodes"] == 12
    assert s["groups_with_leader"] == 4
    assert s["groups_multi_leader"] == 0
    assert s["roles"]["StateLeader"] == 4
    assert s["commit_min"] >= 1
    assert s["commit_apply_lag_max"] <= 32


def test_basic_status_leader_progress():
    state, _ = drive(C=4)
    leaders = np.nonzero(np.asarray(state.role[..., 0]) == ROLE_LEADER)[0]
    st = basic_status(state, SPEC, int(leaders[0]), 0)
    assert st["raft_state"] == "StateLeader"
    assert st["lead"] == int(leaders[0])
    prog = st["progress"]
    assert set(prog) == {0, 1, 2}
    # followers replicating and caught up to within the ack pipeline
    assert all(p["state"] == "StateReplicate" for p in prog.values())
    assert all(p["match"] >= st["commit"] - 2 for p in prog.values())
