"""Scale chaos driver: BASELINE configs #3/#5 on real hardware.

Runs the functional chaos loop (etcd_tpu/harness/chaos.py) at
CHAOS_C groups x CHAOS_ROUNDS rounds with randomized drop/delay/partition
(and, with CHAOS_CRASH > 0, crash–restart; with CHAOS_MEMBER > 0,
membership-change) faults and on-device safety checkers, then prints ONE
JSON line with the violation counts and liveness stats. Evidence files:
CHAOS_r*.json / CHAOS_CRASH_*.json / CHAOS_MEMBER_*.json.

Usage: CHAOS_C=1000000 CHAOS_ROUNDS=200 python chaos_run.py
Crash tier: CHAOS_C=262144 CHAOS_CRASH=0.01 python chaos_run.py
  (CHAOS_DOWN sets the outage length in rounds; CHAOS_DURABILITY=none
  selects the deliberately-broken persist-nothing model, which MUST
  trip the leader-completeness checker — useful to prove the checker
  is live at scale.)
Membership tier: CHAOS_C=4096 CHAOS_CRASH=0.01 CHAOS_MEMBER=0.05 \\
  python chaos_run.py
  (CHAOS_MEMBER_MIX names the conf-change palette — standard / simple /
  shrink; CHAOS_INIT_VOTERS boots partial voter sets, default 3 when the
  tier is on; CHAOS_SNAP_BOOST / CHAOS_MEMBER_BOOST route the crash
  budget through the targeted scheduler, 1 = plain Bernoulli;
  CHAOS_CONFIG_AWARE=0 selects the deliberately config-blind recovery
  checkers, which MUST fire on a remove-voter schedule. Conf-change
  words exceed the int16 wire, so the tier forces CHAOS_WIRE16=0, and
  the liveness floor defaults to the tier's conscious 0.1 instead of
  0.2 — membership churn legally starves fault epochs harder.)

KV apply-plane self-check tier: APPLY_KEYS > 0 runs the device-vs-host
differential parity pass (etcd_tpu/device_mvcc/fuzz.py — the same
harness the fuzz suite drives) after the chaos run and folds a
``kv_plane`` report plus an ``apply_parity_ok`` gate into the JSON line:
  APPLY_KEYS=64 APPLY_GROUPS=256 APPLY_OPS=200 python chaos_run.py
(APPLY_KEYS=0, the default, skips the tier.)

Telemetry / flight recorder (ISSUE 9): TELEM=1 (the default) rides the
FleetTelemetry plane (etcd_tpu/models/telemetry.py) through every epoch
and folds a per-epoch ``timeline`` array (cumulative latency histograms
+ lane totals + violation/crash counters at each epoch boundary) plus a
``telemetry`` summary (p50/p99 propose→commit, election and heal
latencies) into the JSON line — a failing soak is diagnosable post-hoc
epoch by epoch. TELEM=0 disables (bit-identical state trajectory);
TELEM_BUCKETS sets the power-of-two histogram bucket count (2..16);
TELEM_EVERY=N decimates the timeline to every Nth epoch boundary (plus
the final row) so multi-hour soaks don't grow it without bound.

Black-box forensics (ISSUE 15): CHAOS_BLACKBOX=1 rides the EventRing
plane (etcd_tpu/models/blackbox.py) — a per-group [W, M] ring of packed
per-round event words frozen at each group's first violation — and
folds a ``forensics`` section (decoded per-round per-member timelines
for the first CHAOS_BLACKBOX_K violating groups; only those groups'
rings cross PCIe) into the JSON line. CHAOS_BLACKBOX_WINDOW sets the
ring depth W (2..256, default 32). Bit-identical state trajectory.

Fault mix and geometry knobs: CHAOS_DROP / CHAOS_DELAY / CHAOS_PART set
the per-round drop/delay/partition probabilities (defaults 0.02 / 0.05
/ 0.1); CHAOS_SEED seeds the fault PRNG; CHAOS_LIVENESS_FRAC sets the
per-epoch commit-liveness floor (default 0.2, or the membership tier's
conscious 0.1); CHAOS_L sets the log ring length (default 16);
CHAOS_BOUND caps the per-member inbox (default M-1); CHAOS_CHUNKS
splits the fleet into HLO-temp-bounding chunks (defaults to the
bench-proven 131072-wide chunks above 262k groups on accelerators);
CHAOS_SYNC=1 forces synchronous dispatch; CHAOS_LEASE=0 skips the
lease-read tier.

All knobs are validated up front: a probability outside [0, 1], a boost
below 1, an unknown mix/durability name, a TELEM value that is not 0/1,
or an out-of-range APPLY_*/TELEM_* value exits 2 before any device
work. ``--preflight`` additionally runs the donation + one-trace
auditors (etcd_tpu/analysis/audit.py) on the exact epoch program the
knobs select, at a small probe C, and exits 1 on a contract violation
— a long TPU soak fails in seconds instead of hours.
"""
from __future__ import annotations

import json
import os
import sys
import time

import jax


import functools

from etcd_tpu.utils.knobs import (
    env_bool,
    env_float,
    env_int,
    env_str,
    knob_error,
)

# the shared exit-2-before-device-work validation pattern
# (etcd_tpu/utils/knobs.py), bound to this driver's name
_knob_error = functools.partial(knob_error, "chaos_run")
_env_float = functools.partial(env_float, "chaos_run")
_env_int = functools.partial(env_int, "chaos_run")
_env_bool = functools.partial(env_bool, "chaos_run")
_env_str = functools.partial(env_str, "chaos_run")

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

# CHAOS_PRNG=rbg swaps the PRNG for the cheaper hardware generator —
# measured and rejected as the default: the flag is global, so it also
# changes the fleet's election-timeout randomization, and a 262k run left
# 32 groups split-voting past the heal budget (threefry recovers fully).
if _env_str("CHAOS_PRNG", "threefry", ("threefry", "rbg")) == "rbg":
    jax.config.update("jax_default_prng_impl", "rbg")

from etcd_tpu.utils.cache import configure_compile_cache

configure_compile_cache(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    from etcd_tpu.harness.chaos import run_chaos, summarize_chaos
    from etcd_tpu.types import Spec
    from etcd_tpu.utils.config import (
        CrashConfig,
        MemberChaosConfig,
        RaftConfig,
    )

    # --preflight is the only accepted argument (everything else is
    # knob-driven); an unknown flag exits 2 like a bad knob would
    preflight = "--preflight" in sys.argv[1:]
    unknown = [a for a in sys.argv[1:] if a != "--preflight"]
    if unknown:
        print(f"chaos_run: unknown argument(s): {' '.join(unknown)} "
              f"(only --preflight; configure via CHAOS_* knobs)",
              file=sys.stderr)
        return 2

    # ---- knob validation, before any device work (exit code 2).
    # Name/shape validation is delegated to the config dataclasses' own
    # __post_init__ (one source of truth: adding a mix or durability
    # mode there is automatically accepted here); this block only owns
    # the env parsing and the numeric range checks.
    drop_p = _env_float("CHAOS_DROP", "0.02", 0.0, 1.0)
    delay_p = _env_float("CHAOS_DELAY", "0.05", 0.0, 1.0)
    partition_p = _env_float("CHAOS_PART", "0.1", 0.0, 1.0)
    crash_p = _env_float("CHAOS_CRASH", "0", 0.0, 1.0)
    member_p = _env_float("CHAOS_MEMBER", "0", 0.0, 1.0)
    snap_boost = _env_float("CHAOS_SNAP_BOOST", "1", 1.0)
    member_boost = _env_float("CHAOS_MEMBER_BOOST", "1", 1.0)
    # the membership tier's conscious liveness floor is 0.1 (joint
    # configs need both halves to commit; partial-voter boots leave
    # partitioned minorities smaller) — see README chaos tiers
    liveness_frac = _env_float(
        "CHAOS_LIVENESS_FRAC", "0.1" if member_p > 0 else "0.2", 0.0, 1.0)
    init_voters = _env_int("CHAOS_INIT_VOTERS",
                           "3" if member_p > 0 else "0")
    down_rounds = _env_int("CHAOS_DOWN", "3")
    try:
        crash_knobs = CrashConfig(
            down_rounds=down_rounds,
            durability=_env_str("CHAOS_DURABILITY", "stable"),
        )
        member_cfg = MemberChaosConfig(
            mix=_env_str("CHAOS_MEMBER_MIX", "standard"),
            initial_voters=init_voters,
            snap_crash_boost=snap_boost,
            member_crash_boost=member_boost,
        )
    except ValueError as e:
        _knob_error(str(e))
    # KV apply-plane tier knobs (device_mvcc differential parity pass);
    # APPLY_KEYS caps at the 9-bit op-word key field (scheme.MAX_KEYS)
    apply_knobs = {
        name: _env_int(name, default, lo, hi)
        for name, default, lo, hi in (("APPLY_KEYS", "0", 0, 511),
                                      ("APPLY_GROUPS", "256", 1, None),
                                      ("APPLY_OPS", "200", 1, None))
    }
    # telemetry plane / flight recorder (models/telemetry.py): on by
    # default — the timeline costs one tiny host transfer per epoch
    telem = _env_bool("TELEM", "1")
    telem_buckets = _env_int("TELEM_BUCKETS", "8", 2, 16)
    telem_every = _env_int("TELEM_EVERY", "1", 1, None)
    # black-box forensics plane (models/blackbox.py): off by default —
    # the ring adds a [W, M, C] i32 resident buffer
    blackbox = _env_bool("CHAOS_BLACKBOX", "0")
    blackbox_k = _env_int("CHAOS_BLACKBOX_K", "4", 1, None)
    blackbox_window = _env_int("CHAOS_BLACKBOX_WINDOW", "32", 2, 256)
    seed = _env_int("CHAOS_SEED", "0")
    config_aware = _env_bool("CHAOS_CONFIG_AWARE", "1")
    sync_dispatch = _env_bool("CHAOS_SYNC", "0")
    lease_tier = _env_bool("CHAOS_LEASE", "1")

    wire16_knob = _env_bool("CHAOS_WIRE16", "1")
    if member_p > 0 and "CHAOS_WIRE16" in os.environ and wire16_knob:
        _knob_error("CHAOS_MEMBER needs the int32 wire (conf-change words "
                    "use bits 16-20); unset CHAOS_WIRE16")

    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)
    C = _env_int("CHAOS_C", str(262_144 if on_accel else 1_000), 1, None)
    rounds = _env_int("CHAOS_ROUNDS", "200", 1, None)

    # bench geometry (bench.py Spec + RaftConfig) so the chaos tier proves
    # the MEASURED headline configuration safe under faults: K=2 slots,
    # L=16 ring, int16 wire, inbox_bound=M-1. Bounded-inbox compaction and
    # the int16 wire are legal under chaos for the same reason they are in
    # steady state — anything the bound evicts is a droppable message (the
    # transport contract already drops via keep-masks), and it is counted.
    L = _env_int("CHAOS_L", "16", 1, None)
    spec = Spec(M=5, L=L, E=1, K=2, W=4, R=2, A=2)
    if init_voters > spec.M:
        # silently collapsing to the all-voters boot would defeat the
        # partial-voter-set intent (no free slots for add words)
        _knob_error(f"CHAOS_INIT_VOTERS={init_voters} exceeds the member "
                    f"count M={spec.M}")
    bound = _env_int("CHAOS_BOUND", str(spec.M - 1), 0, None)
    # the membership tier needs the int32 wire (validated above): its
    # conf-change words ride MsgProp/MsgApp ent_data and use bits 16-20
    wire16 = wire16_knob and member_p == 0
    # fleet chunking caps the round program's HLO temporaries, exactly as
    # in bench.py — above ~262k resident groups the un-chunked chaos
    # round overflows HBM by mere tens of MB. Chunks of 131,072 (the
    # bench-proven shape) run clean; 262,144-wide chunks at C=524k
    # reproducibly crashed the TPU worker.
    chunks = _env_int(
        "CHAOS_CHUNKS",
        str(max(1, C // 131072)) if on_accel and C > 262144 else "1",
        1, None,
    )
    cfg = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=4,
                     inbox_bound=bound, coalesce_commit_refresh=True,
                     wire_int16=wire16, fleet_chunks=chunks)

    epoch_len, heal_len = 50, 25
    # crash–restart faults (CrashConfig durability model): off by default
    # so the legacy network-fault evidence runs stay bit-identical.
    # crash_knobs/member_cfg were validated up front; member_cfg is
    # always passed — its crash-boost knobs target snapshot windows in
    # pure crash runs too (run_chaos gates the palette on member_p)
    crash_cfg = crash_knobs if crash_p > 0 else None

    if preflight:
        # audit the EXACT epoch program these knobs select — same
        # structure flags run_chaos will derive, at a small probe C —
        # before the fleet is allocated at CHAOS_C (donation + one-trace
        # contracts; etcd_tpu/analysis/audit.py)
        from etcd_tpu.analysis.audit import run_preflight
        from etcd_tpu.analysis.programs import chaos_epoch_program

        inst = chaos_epoch_program(
            cfg, spec,
            with_delay=delay_p > 0,
            with_crash=crash_p > 0,
            with_member=member_p > 0,
            with_telemetry=telem,
            with_blackbox=blackbox,
            blackbox_window=blackbox_window,
            buckets=telem_buckets,
        )
        finds = run_preflight(
            inst, progress=lambda m: print(f"# {m}", file=sys.stderr))
        if finds:
            for f in finds:
                print(f, file=sys.stderr)
            print(f"# preflight: {len(finds)} contract violation(s)",
                  file=sys.stderr)
            return 1
        print("# preflight ok", file=sys.stderr)

    t0 = time.perf_counter()
    rep = run_chaos(
        spec, cfg, C=C, rounds=rounds, epoch_len=epoch_len, heal_len=heal_len,
        seed=seed,
        drop_p=drop_p, delay_p=delay_p, partition_p=partition_p,
        crash_p=crash_p, crash=crash_cfg,
        member_p=member_p, member=member_cfg,
        config_aware=config_aware,
        sync_dispatch=sync_dispatch,
        telemetry=telem, telemetry_buckets=telem_buckets,
        telemetry_every=telem_every,
        blackbox=blackbox, blackbox_window=blackbox_window,
        blackbox_k=blackbox_k,
    )
    rep["elapsed_s"] = round(time.perf_counter() - t0, 1)
    rep["platform"] = platform
    # safety/recovery/liveness gates (harness/chaos.py summarize_chaos —
    # the same pure function the tests drive)
    rep.update(summarize_chaos(
        rep, rounds=rounds, epoch_len=epoch_len, heal_len=heal_len,
        liveness_frac=liveness_frac,
    ))

    # host-layer lease chaos (tester/stresser_lease.go +
    # checker_lease_expire.go analogs): stress/expire leases through
    # keep-mask faults on a small hosted cluster. CHAOS_LEASE=0 skips.
    if lease_tier:
        # host-layer tiers in a CPU subprocess: an EtcdCluster step is a
        # C=1 device dispatch, ~3.5s/op over the TPU tunnel but
        # milliseconds on host CPU, and the tiers prove host-layer
        # semantics that don't depend on the device tier's platform
        import subprocess

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)
        # degrade gracefully on ANY tier failure (hang, crash, torn
        # stdout): the device tier's hours of results must survive
        try:
            out = subprocess.run(
                [sys.executable, "-m", "etcd_tpu.harness.chaos_lease",
                 "--seed", str(seed)],
                capture_output=True, text=True, env=env, timeout=1800,
            )
            lines = [ln for ln in out.stdout.splitlines()
                     if ln.startswith("{")]
            if out.returncode != 0 or not lines:
                raise RuntimeError((out.stderr or out.stdout)[-500:])
            lrep = json.loads(lines[-1])
            lease_safe = (
                not lrep["lease_violations"]
                # the r5 gates: bounded indeterminacy + a request
                # failure rate a retrying stresser actually sustains
                and not lrep.get("lease_gate_failures")
                and lrep["runner_exclusion_violations"] == 0
                and lrep["runner_final_progress"]
            )
            rep.update(lrep)
            rep["lease_safe"] = lease_safe
        except Exception as e:  # noqa: BLE001 — ANY tier failure must not
            # discard the device tier's (hours-long) results
            rep["lease_safe"] = False
            rep["lease_tier_error"] = f"{type(e).__name__}: {e}"[-500:]
    else:
        rep["lease_safe"] = True

    # KV apply-plane differential parity tier (device_mvcc/fuzz.py): the
    # device revision store vs per-schedule host MVCCStore replays under
    # the shared canonical digest — proves the served-write plane's apply
    # semantics on THIS platform alongside the chaos evidence. Degrades
    # gracefully like the lease tier: a tier failure must not discard the
    # device tier's results.
    if apply_knobs["APPLY_KEYS"] > 0:
        try:
            from etcd_tpu.device_mvcc import KVSpec
            from etcd_tpu.device_mvcc.fuzz import differential_run

            rep["kv_plane"] = differential_run(
                KVSpec(keys=apply_knobs["APPLY_KEYS"]),
                groups=apply_knobs["APPLY_GROUPS"],
                ops=apply_knobs["APPLY_OPS"],
                seed=seed,
            )
            rep["apply_parity_ok"] = rep["kv_plane"]["parity_ok"]
        except Exception as e:  # noqa: BLE001
            rep["apply_parity_ok"] = False
            rep["kv_plane_error"] = f"{type(e).__name__}: {e}"[-500:]
    else:
        rep["apply_parity_ok"] = True

    print(json.dumps(rep))
    ok = (rep["safe"] and rep["recovered"] and rep["lively"]
          and rep["lease_safe"] and rep["apply_parity_ok"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
