"""The v2 HTTP proxy — director + reverse-forwarder analog.

Re-design of ``server/proxy/httpproxy`` (director.go, reverse.go,
proxy.go): a director keeps the endpoint set fresh from a URL source
(static list or discovery ``get_cluster``), marks endpoints unavailable
for ``failure_wait`` seconds when a forward fails, and the proxy tries
available endpoints in order — 503 with the reference's message when
none remain (reverse.go:100-107).

Transport is pluggable: ``transport(url, method, path, form)`` returns
``(status, body, headers)`` — in-process fakes in tests, a urllib
round-trip against gateway servers in deployment.
"""
from __future__ import annotations

import time as _time
from typing import Callable

DEFAULT_REFRESH_INTERVAL = 30.0  # director.go:28 (30000ms)
DEFAULT_FAILURE_WAIT = 5.0       # etcdmain proxy-failure-wait default


class Endpoint:
    """director.go endpoint: URL + availability latch."""

    def __init__(self, url: str, clock: Callable[[], float]):
        self.url = url
        self.available = True
        self._clock = clock
        self._failed_at = 0.0

    def failed(self, wait: float) -> None:
        self.available = False
        self._failed_at = self._clock()
        self._wait = wait

    def maybe_recover(self) -> None:
        # the deferred goroutine of director.go endpoint.Failed: the
        # endpoint returns to rotation after failureWait
        if not self.available and \
                self._clock() - self._failed_at >= self._wait:
            self.available = True


class Director:
    """director.go director: refresh endpoints from urls_fn."""

    def __init__(self, urls_fn: Callable[[], list[str]],
                 failure_wait: float = DEFAULT_FAILURE_WAIT,
                 refresh_interval: float = DEFAULT_REFRESH_INTERVAL,
                 clock: Callable[[], float] | None = None):
        self.urls_fn = urls_fn
        self.failure_wait = failure_wait
        self.refresh_interval = refresh_interval
        self.clock = clock or _time.time
        self._eps: list[Endpoint] = []
        self._last_refresh = -1e18
        self.refresh()

    def refresh(self) -> None:
        self._last_refresh = self.clock()
        by_url = {e.url: e for e in self._eps}
        self._eps = [by_url.get(u) or Endpoint(u, self.clock)
                     for u in self.urls_fn()]

    def _maybe_refresh(self) -> None:
        if self.clock() - self._last_refresh >= self.refresh_interval:
            self.refresh()

    def endpoints(self) -> list[Endpoint]:
        """Available endpoints only (director.go endpoints())."""
        self._maybe_refresh()
        for e in self._eps:
            e.maybe_recover()
        return [e for e in self._eps if e.available]


class HTTPProxy:
    """reverse.go reverseProxy.ServeHTTP: try endpoints in order,
    marking failures, 503 when the rotation is empty."""

    def __init__(self, director: Director,
                 transport: Callable[[str, str, str, dict],
                                     tuple[int, dict, dict]]):
        self.director = director
        self.transport = transport

    def handle(self, method: str, path: str,
               form: dict | None = None) -> tuple[int, dict, dict]:
        eps = self.director.endpoints()
        if not eps:
            return 503, {"message":
                         "httpproxy: zero endpoints currently available"
                         }, {}
        for ep in eps:
            try:
                return self.transport(ep.url, method, path, form or {})
            except Exception:
                # reverse.go:139-151: transport error -> mark endpoint
                # unavailable and try the next one
                ep.failed(self.director.failure_wait)
        return 503, {"message":
                     "httpproxy: unable to get response from "
                     f"{len(eps)} endpoint(s)"}, {}


def make_urllib_transport(tls):
    """A transport bound to a client TLS config (transport.TLSInfo or
    ssl.SSLContext) so the proxy can front HTTPS gateways — the
    reference proxy dials upstream TLS from --peer/client cert flags
    (etcdmain/gateway.go, proxy/httpproxy)."""
    from etcd_tpu.transport import resolve_client_context

    ctx = resolve_client_context(tls)

    def transport(url: str, method: str, path: str,
                  form: dict) -> tuple[int, dict, dict]:
        import json
        import urllib.error
        import urllib.parse
        import urllib.request

        data = urllib.parse.urlencode(form).encode() if form else None
        req = urllib.request.Request(
            url + path, data=data, method=method,
            headers={"Content-Type": "application/x-www-form-urlencoded"})
        try:
            with urllib.request.urlopen(req, timeout=5,
                                        context=ctx) as resp:
                return (resp.status, json.loads(resp.read()),
                        dict(resp.headers))
        except urllib.error.HTTPError as e:
            # HTTP-level errors are valid proxy responses, not endpoint
            # failures (reverse.go forwards them through)
            return e.code, json.loads(e.read()), dict(e.headers)

    return transport


# Back-compat plain-HTTP transport (the pre-TLS symbol), built ONCE at
# module load — not per request.
urllib_transport = make_urllib_transport(None)
