"""etcd_tpu: a TPU-native batched Raft consensus simulation engine.

The capabilities of etcd's `raft/` stack (reference: Monokaix/etcd),
re-designed TPU-first: vmapped pure step functions over [clusters, members]
struct-of-arrays state, dense message tensors exchanged by transpose /
collectives, and fault injection as keep-masks. See SURVEY.md at the repo
root for the full mapping to the reference.
"""
from etcd_tpu.types import Spec
from etcd_tpu.utils.config import RaftConfig

__all__ = ["Spec", "RaftConfig"]
