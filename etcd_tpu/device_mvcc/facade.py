"""Host facade over a fleet of device-resident MVCC stores.

``DevicePlane`` owns one ``KVState`` fleet (C lanes, clusters-minor) and
gives host code an imperative per-lane surface: encode the op, dispatch
ONE jitted masked apply, read back the lanes it needs.  This is the
kvserver-facing half of the apply plane — the batched/high-throughput
path goes through ``models/engine.py:build_kv_round`` instead and never
leaves the device.

Programs are cached per KVSpec (module-level lru_cache, mirroring
engine._jitted_round): every EtcdCluster in a suite shares two compiled
programs (apply + digest) per key-space size.

Layering: this module returns plain numpy records; the KeyValue/Event
materialization lives in the server layer (server/mvcc.py
DeviceBackedStore, server/watch.py events_from_delta) so device_mvcc
never imports server code.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from etcd_tpu.device_mvcc import scheme
from etcd_tpu.device_mvcc.apply import apply_word, kv_digest
from etcd_tpu.device_mvcc.state import KVSpec, KVState, init_kv


@functools.lru_cache(maxsize=16)
def _jitted_apply(kvspec: KVSpec):
    return jax.jit(functools.partial(apply_word, kvspec))


@functools.lru_cache(maxsize=16)
def _jitted_digest(kvspec: KVSpec):
    return jax.jit(functools.partial(kv_digest, kvspec))


class DevicePlane:
    """C independent device MVCC lanes (one per hosted member)."""

    def __init__(self, kvspec: KVSpec | None = None, C: int = 1):
        self.kvspec = kvspec or KVSpec()
        self.C = C
        self.st = init_kv(self.kvspec, C)
        self._apply = _jitted_apply(self.kvspec)
        self._digest = _jitted_digest(self.kvspec)

    # -- raw word application ----------------------------------------------
    def apply_word_lane(self, lane: int, word: int) -> None:
        active = jnp.zeros((self.C,), jnp.bool_).at[lane].set(True)
        self.st = self._apply(self.st, jnp.int32(word), active)

    # -- lane readbacks ------------------------------------------------------
    def current_rev(self, lane: int) -> int:
        return int(np.asarray(self.st.current_rev[lane]))

    def compact_rev(self, lane: int) -> int:
        return int(np.asarray(self.st.compact_rev[lane]))

    def err_counts(self, lane: int) -> tuple[int, int]:
        return (
            int(np.asarray(self.st.err_compacted[lane])),
            int(np.asarray(self.st.err_future[lane])),
        )

    def digest(self, lane: int) -> int:
        return int(np.asarray(self._digest(self.st)[lane]))

    def records(self, lane: int) -> dict[int, dict]:
        """Latest records of one lane: {key_id: {mod, create, version,
        vword, lease, tomb}} for present keys (tombstones included)."""
        sub = jax.tree.map(lambda x: np.asarray(x[..., lane]), self.st)
        out = {}
        for kid in np.nonzero(sub.present)[0]:
            kid = int(kid)
            out[kid] = {
                "mod": int(sub.mod[kid]),
                "create": int(sub.create[kid]),
                "version": int(sub.version[kid]),
                "vword": int(sub.vword[kid]),
                "lease": int(sub.lease[kid]),
                "tomb": bool(sub.tomb[kid]),
            }
        return out

    # -- lane restore (peer-snapshot install path) --------------------------
    def load_lane(self, lane: int, records: dict[int, dict],
                  current_rev: int, compact_rev: int) -> None:
        """Overwrite one lane from latest-record tuples (the applySnapshot
        analog for the device plane: the lane jumps to the snapshot)."""
        K = self.kvspec.keys
        cols = {
            "present": np.zeros(K, bool), "tomb": np.zeros(K, bool),
            "mod": np.zeros(K, np.int32), "create": np.zeros(K, np.int32),
            "version": np.zeros(K, np.int32), "vword": np.zeros(K, np.int32),
            "lease": np.zeros(K, np.int32),
        }
        for kid, r in records.items():
            cols["present"][kid] = True
            cols["tomb"][kid] = r["tomb"]
            for f in ("mod", "create", "version", "vword", "lease"):
                cols[f][kid] = r[f]
        upd = {}
        for f, col in cols.items():
            leaf = np.array(getattr(self.st, f))
            leaf[:, lane] = col
            upd[f] = jnp.asarray(leaf)
        for f, v in (("current_rev", current_rev),
                     ("compact_rev", compact_rev), ("txn_main", 0)):
            leaf = np.array(getattr(self.st, f))
            leaf[lane] = v
            upd[f] = jnp.asarray(leaf)
        self.st = self.st.replace(**upd)
