"""Env-knob parsing with the exit-2-before-device-work contract.

The scale drivers (bench.py, chaos_run.py) validate every env knob up
front and exit 2 with a pointed one-line message on a bad value, before
any device work — the contract the knob exit-code tests
(tests/test_recovery_member.py, tests/test_device_mvcc.py) enforce.
This module is the single copy of that pattern; drivers bind their
program name via functools.partial.
"""
from __future__ import annotations

import os
import sys


def knob_error(prog: str, msg: str) -> "NoReturn":  # noqa: F821 — py3.9
    print(f"{prog}: {msg}", file=sys.stderr)
    raise SystemExit(2)


def env_float(prog: str, name: str, default: str,
              lo: float | None = None, hi: float | None = None) -> float:
    raw = os.environ.get(name, default)
    try:
        v = float(raw)
    except ValueError:
        knob_error(prog, f"{name}={raw!r} is not a number")
    if v != v:  # NaN compares False against any range bound
        knob_error(prog, f"{name}={raw!r} is not a number")
    if lo is not None and v < lo or hi is not None and v > hi:
        span = (f"[{lo}, {hi}]" if hi is not None else f">= {lo}")
        knob_error(prog, f"{name}={raw} outside {span}")
    return v


def env_int(prog: str, name: str, default: str | None,
            lo: int | None = None, hi: int | None = None) -> int | None:
    raw = os.environ.get(name, default)
    if raw is None:
        return None
    try:
        v = int(raw)
    except ValueError:
        knob_error(prog, f"{name}={raw!r} is not an integer")
    if (lo is not None and v < lo) or (hi is not None and v > hi):
        span = (f"[{lo}, {hi}]" if hi is not None else f">= {lo}")
        knob_error(prog, f"{name}={raw} outside {span}")
    return v


def env_bool(prog: str, name: str, default: str) -> bool:
    """0/1 flag with the same exit-2 contract (a typo'd BENCH_PACKED=yes
    must not silently select the 0 branch)."""
    raw = os.environ.get(name, default)
    if raw not in ("0", "1"):
        knob_error(prog, f"{name}={raw!r} is not 0 or 1")
    return raw == "1"


def env_str(prog: str, name: str, default: str,
            choices: tuple[str, ...] | None = None) -> str:
    """String knob; with ``choices`` a value outside the set exits 2 (a
    typo'd CHAOS_DURABILITY=stabel must not silently run a different
    durability model)."""
    raw = os.environ.get(name, default)
    if choices is not None and raw not in choices:
        knob_error(prog, f"{name}={raw!r} is not one of {'/'.join(choices)}")
    return raw


def env_list(prog: str, name: str, default: str,
             choices: tuple[str, ...]) -> tuple[str, ...]:
    """Comma-separated selection knob with the same exit-2 contract.
    "all" (the usual default) expands to every choice; any element
    outside ``choices`` exits 2 (a typo'd ANALYSIS_RULES=hostsync must
    not silently run zero rules). Order and duplicates are normalized to
    the declaration order of ``choices``."""
    raw = os.environ.get(name, default)
    if raw == "all":
        return tuple(choices)
    parts = tuple(p.strip() for p in raw.split(",") if p.strip())
    if not parts:
        knob_error(prog, f"{name}={raw!r} selects nothing")
    for p in parts:
        if p not in choices:
            knob_error(prog,
                       f"{name}: {p!r} is not one of {'/'.join(choices)}")
    return tuple(c for c in choices if c in parts)
