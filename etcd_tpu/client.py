"""Client façade — the clientv3 analog.

Mirrors ``client/v3``'s surface (client.go / kv.go / watch.go / lease.go /
txn.go op-builders) over an in-process :class:`EtcdCluster`, the way the
reference embeds a client via `api/v3client`. Namespacing (client/v3/
namespace) is a constructor option; retry/balancer machinery collapses away
because transport faults surface as engine-level mask faults, not RPC
errors.
"""
from __future__ import annotations

import dataclasses
from typing import Any

from etcd_tpu.server.kvserver import Compare, EtcdCluster, Op


def prefix_range_end(prefix: bytes) -> bytes:
    """clientv3.GetPrefixRangeEnd (client/v3/op.go): increment the last
    byte that can be incremented; all-0xff prefixes scan to end."""
    end = bytearray(prefix)
    for i in range(len(end) - 1, -1, -1):
        if end[i] < 0xFF:
            end[i] += 1
            return bytes(end[: i + 1])
    return b"\x00"


@dataclasses.dataclass
class TxnBuilder:
    """clientv3.Txn: If(...).Then(...).Else(...).Commit()."""

    client: "Client"
    _compare: list[Compare] = dataclasses.field(default_factory=list)
    _success: list[Op] = dataclasses.field(default_factory=list)
    _failure: list[Op] = dataclasses.field(default_factory=list)

    def if_(self, *cmps: Compare) -> "TxnBuilder":
        self._compare.extend(cmps)
        return self

    def then(self, *ops: Op) -> "TxnBuilder":
        self._success.extend(ops)
        return self

    def else_(self, *ops: Op) -> "TxnBuilder":
        self._failure.extend(ops)
        return self

    def commit(self) -> dict:
        return self.client.ec.txn(
            self._compare,
            [self.client._ns_op(o) for o in self._success],
            [self.client._ns_op(o) for o in self._failure],
            token=self.client.token,
        )


class Client:
    def __init__(self, ec: EtcdCluster, namespace: bytes = b"",
                 token: str | None = None):
        self.ec = ec
        self.ns = namespace
        self.token = token

    # -- namespacing (client/v3/namespace) -----------------------------------
    def _key(self, key: bytes) -> bytes:
        return self.ns + key

    def _range_end(self, key: bytes, range_end: bytes | None):
        if range_end is None:
            return None
        if range_end == b"\x00":
            return prefix_range_end(self.ns) if self.ns else b"\x00"
        return self.ns + range_end

    def _ns_op(self, op: Op) -> Op:
        return dataclasses.replace(
            op, key=self._key(op.key),
            range_end=self._range_end(op.key, op.range_end),
        )

    def _strip(self, kvs):
        """Return prefix-stripped COPIES — range hands back the store's own
        KeyValue objects, which must stay immutable."""
        if not self.ns:
            return kvs
        return [
            dataclasses.replace(kv, key=kv.key[len(self.ns):]) for kv in kvs
        ]

    # -- KV ------------------------------------------------------------------
    def put(self, key: bytes, value: bytes, lease: int = 0,
            prev_kv: bool = False) -> dict:
        return self.ec.put(self._key(key), value, lease, prev_kv, self.token)

    def get(self, key: bytes, rev: int = 0, serializable: bool = False,
            member: int | None = None):
        res = self.ec.range(
            self._key(key), rev=rev, serializable=serializable, member=member,
            token=self.token,
        )
        kvs = self._strip(res["kvs"])
        return kvs[0] if kvs else None

    def get_range(self, key: bytes, range_end: bytes | None = None, **kw):
        res = self.ec.range(
            self._key(key), self._range_end(key, range_end),
            token=self.token, **kw,
        )
        res["kvs"] = self._strip(res["kvs"])
        return res

    def get_prefix(self, prefix: bytes, **kw):
        return self.get_range(prefix, prefix_range_end(prefix), **kw)

    def delete(self, key: bytes, range_end: bytes | None = None,
               prev_kv: bool = False):
        return self.ec.delete_range(
            self._key(key), self._range_end(key, range_end), prev_kv, self.token
        )

    def delete_prefix(self, prefix: bytes):
        return self.delete(prefix, prefix_range_end(prefix))

    def compact(self, rev: int):
        return self.ec.compact(rev)

    def txn(self) -> TxnBuilder:
        return TxnBuilder(self)

    # compare builders (client/v3/compare.go)
    def compare_value(self, key, result, value) -> Compare:
        return Compare(self._key(key), "value", result, value)

    def compare_version(self, key, result, version) -> Compare:
        return Compare(self._key(key), "version", result, version)

    def compare_create(self, key, result, rev) -> Compare:
        return Compare(self._key(key), "create", result, rev)

    def compare_mod(self, key, result, rev) -> Compare:
        return Compare(self._key(key), "mod", result, rev)

    # -- watch ---------------------------------------------------------------
    def watch(self, key: bytes, range_end: bytes | None = None,
              start_rev: int = 0, prev_kv: bool = False,
              member: int | None = None, filters: tuple = (),
              progress_notify: bool = False, fragment: bool = False):
        """clientv3 WatchCreateRequest options: `filters` drops event types
        ("put"/"delete" — WithFilterPut/WithFilterDelete), `progress_notify`
        = WithProgressNotify, `fragment` = WithFragment."""
        m = member if member is not None else self.ec.ensure_leader()
        w = self.ec.watch(
            m, self._key(key), self._range_end(key, range_end), start_rev,
            prev_kv, fragment=fragment, progress_notify=progress_notify,
            filters=filters,
        )
        return _WatchHandle(self, m, w.id)

    def watch_prefix(self, prefix: bytes, **kw):
        return self.watch(prefix, prefix_range_end(prefix), **kw)

    # -- lease ---------------------------------------------------------------
    def lease_grant(self, lease_id: int, ttl: int):
        return self.ec.lease_grant(lease_id, ttl)

    def lease_revoke(self, lease_id: int):
        return self.ec.lease_revoke(lease_id)

    def lease_keepalive(self, lease_id: int):
        return self.ec.lease_keepalive(lease_id)

    # -- auth ----------------------------------------------------------------
    def login(self, name: str, password: str) -> "Client":
        return Client(self.ec, self.ns, self.ec.authenticate(name, password))


@dataclasses.dataclass
class _WatchHandle:
    client: Client
    member: int
    watch_id: int

    def request_progress(self) -> int | None:
        """clientv3 Watcher.RequestProgress: current revision once this
        watcher is fully synced, else None."""
        return self.client.ec.watch_progress(self.member, self.watch_id)

    def events(self):
        evs = self.client.ec.watch_events(self.member, self.watch_id)
        if self.client.ns:
            evs = [
                dataclasses.replace(
                    e, kv=dataclasses.replace(
                        e.kv, key=e.kv.key[len(self.client.ns):]
                    )
                )
                if e.kv.key.startswith(self.client.ns) else e
                for e in evs
            ]
        return evs

    def cancel(self) -> bool:
        return self.client.ec.cancel_watch(self.member, self.watch_id)
