import os

# Tests run on a virtual 8-device CPU mesh: sharding paths are exercised
# without TPU hardware and unit tests stay fast and hermetic.
#
# NOTE: this environment's sitecustomize registers an "axon" TPU backend and
# *explicitly* sets jax_platforms="axon,cpu" via jax.config.update at
# interpreter start, which overrides JAX_PLATFORMS from the environment. We
# must override it back AFTER importing jax, or every eager op dispatches
# over the TPU tunnel (~5ms/op, and hangs when the tunnel is down).
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the round program is large; re-running the
# suite should not re-pay XLA compile time.
os.makedirs("/root/repo/.jax_cache", exist_ok=True)
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
