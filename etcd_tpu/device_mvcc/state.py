"""Device-resident MVCC revision store as a struct-of-arrays pytree.

The batched analog of ``MVCCStore`` (etcd_tpu/server/mvcc.py) restricted
to the canonical fixed key space (device_mvcc/scheme.py): one group's
store is a bundle of ``[keys]`` per-key lanes plus per-group revision
cursors; a fleet is the same pytree with the clusters axis MINOR
(``[keys, C]`` / ``[C]`` leaves), matching the engine's clusters-minor
layout (models/engine.py: TPU (8,128) tiling pads only the small keys
axis, and the apply kernel slots into the round program with the same
``in_axes=-1`` convention).

Latest-record semantics: each key slot holds the key's NEWEST revision
record — exactly what ``mvccpb.KeyValue`` carries (mod/create/version/
value/lease) plus the tombstone mask that stands in for an uncompacted
tombstone generation.  History below the latest record is not
materialized on device; reads below a key's mod_revision answer
``ErrCompacted`` (the plane's effective per-key compaction floor is the
latest record — see apply.read_at).  Everything the digest, the watch
delta scan, and the served-write path need IS the latest record, which
is what makes the fixed-width layout possible.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from flax import struct

from etcd_tpu.device_mvcc import scheme


@dataclasses.dataclass(frozen=True)
class KVSpec:
    """Static shape parameters of the device revision store (the Spec
    analog for the apply plane; array shapes + trace structure only)."""

    keys: int = 64  # fixed key-space size (canonical slots 0..keys-1)

    def __post_init__(self):
        if not 1 <= self.keys <= scheme.MAX_KEYS:
            raise ValueError(
                f"KVSpec.keys ({self.keys}) outside [1, {scheme.MAX_KEYS}] "
                "(the op-word key field is 9 bits)"
            )


class KVState(struct.PyTreeNode):
    # --- per-key latest records (mvccpb.KeyValue analog), [keys, C] --------
    present: jnp.ndarray   # bool: key exists in the index (incl. tombstoned)
    tomb: jnp.ndarray      # bool: latest record is an uncompacted tombstone
    mod: jnp.ndarray       # i32 mod_revision (main)
    create: jnp.ndarray    # i32 create_revision (0 for tombstones)
    version: jnp.ndarray   # i32 (0 for tombstones)
    vword: jnp.ndarray     # i32 value word (the replicated value reference)
    lease: jnp.ndarray     # i32 lease id (0 = none)

    # --- per-group cursors (kvstore.go:59-87 analog), [C] ------------------
    current_rev: jnp.ndarray  # i32, boots at 1 like the reference
    compact_rev: jnp.ndarray  # i32
    txn_main: jnp.ndarray     # i32 revision main of the open txn (CONT words)

    # --- per-group status lanes (host exceptions become counters) ----------
    err_compacted: jnp.ndarray  # i32 ErrCompacted count (compact below floor)
    err_future: jnp.ndarray     # i32 ErrFutureRev count (compact past head)

    # --- engine apply-frontier bookkeeping, [C] ----------------------------
    applied_idx: jnp.ndarray  # i32 log index applied into this store
    skipped: jnp.ndarray      # i32 words lost to ring-overwrite overrun
    desynced: jnp.ndarray     # bool, sticky: the bound member installed a
    #   peer snapshot (applied jumped > Spec.A in one round) — its ring
    #   slots no longer index-match, so the lane FREEZES instead of
    #   replaying stale words (engine.build_kv_round)


def init_kv(kvspec: KVSpec, C: int) -> KVState:
    """Fresh fleet store: empty key space at revision 1."""
    K = kvspec.keys
    zKC = jnp.zeros((K, C), jnp.int32)
    fKC = jnp.zeros((K, C), jnp.bool_)
    zC = jnp.zeros((C,), jnp.int32)
    return KVState(
        present=fKC, tomb=fKC, mod=zKC, create=zKC, version=zKC,
        vword=zKC, lease=zKC,
        current_rev=jnp.ones((C,), jnp.int32), compact_rev=zC,
        txn_main=zC, err_compacted=zC, err_future=zC,
        applied_idx=zC, skipped=zC, desynced=jnp.zeros((C,), jnp.bool_),
    )
