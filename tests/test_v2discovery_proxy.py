"""v2discovery bootstrap flow + httpproxy director/failover
(v2discovery/discovery.go, proxy/httpproxy/{director,reverse}.go)."""
import pytest

from etcd_tpu import clientv2, discovery
from etcd_tpu.httpproxy import Director, HTTPProxy
from etcd_tpu.server.kvserver import EtcdCluster
from etcd_tpu.server.v2http import V2Api


@pytest.fixture(scope="module")
def disco_keys():
    """The discovery service: any v2-serving cluster."""
    ec = EtcdCluster(n_members=3)
    ec.ensure_leader()
    return clientv2.new(V2Api(ec)).keys


def fresh_token(keys, name, size):
    discovery.create_token(keys, name, size)
    return name


# ---------------------------------------------------------- discovery

def test_join_cluster_three_members(disco_keys):
    keys = disco_keys
    tok = fresh_token(keys, "tok3", 3)
    regs = [(10, "m0=http://h0:2380"), (11, "m1=http://h1:2380"),
            (12, "m2=http://h2:2380")]

    pending = list(regs[1:])

    def register_next():
        if pending:
            mid, cfg = pending.pop(0)
            discovery.Discovery(keys, tok, mid)._create_self(cfg)

    d0 = discovery.Discovery(keys, tok, regs[0][0],
                             wait_hook=register_next)
    cluster = d0.join_cluster(regs[0][1])
    assert cluster == "m0=http://h0:2380,m1=http://h1:2380,m2=http://h2:2380"
    # a later joiner sees the already-complete set without waiting
    d2 = discovery.Discovery(keys, tok, regs[2][0])
    assert d2.get_cluster() == cluster


def test_join_duplicate_id(disco_keys):
    keys = disco_keys
    tok = fresh_token(keys, "tokdup", 2)
    d = discovery.Discovery(keys, tok, 7)
    d._create_self("a=http://a:2380")
    with pytest.raises(discovery.ErrDuplicateID):
        discovery.Discovery(keys, tok, 7).join_cluster("a=http://a:2380")


def test_join_full_cluster(disco_keys):
    keys = disco_keys
    tok = fresh_token(keys, "tokfull", 1)
    discovery.Discovery(keys, tok, 1).join_cluster("a=http://a:2380")
    with pytest.raises(discovery.ErrFullCluster):
        discovery.Discovery(keys, tok, 2).join_cluster("b=http://b:2380")
    # observers can still read the full cluster
    assert discovery.Discovery(keys, tok, 99).get_cluster() == \
        "a=http://a:2380"


def test_size_key_missing_and_bad(disco_keys):
    keys = disco_keys
    with pytest.raises(discovery.ErrSizeNotFound):
        discovery.Discovery(keys, "tok404", 1).join_cluster("a=u")
    discovery.create_token(keys, "tokbad", 0)
    keys.set("/tokbad/_config/size", "zero")
    with pytest.raises(discovery.ErrBadSizeKey):
        discovery.Discovery(keys, "tokbad", 1).join_cluster("a=u")


def test_duplicate_name_rejected(disco_keys):
    keys = disco_keys
    tok = fresh_token(keys, "tokname", 2)
    discovery.Discovery(keys, tok, 1)._create_self("same=http://a:2380")
    discovery.Discovery(keys, tok, 2)._create_self("same=http://b:2380")
    with pytest.raises(discovery.ErrDuplicateName):
        discovery.Discovery(keys, tok, 1).get_cluster()


def test_wait_times_out_without_peers(disco_keys):
    keys = disco_keys
    tok = fresh_token(keys, "tokwait", 3)
    d = discovery.Discovery(keys, tok, 5)
    d.MAX_WAIT_POLLS = 3
    with pytest.raises(discovery.ErrTooManyRetries):
        d.join_cluster("only=http://x:2380")


# ---------------------------------------------------------- httpproxy

class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def ok_transport(tag):
    def t(url, method, path, form):
        return 200, {"served_by": url, "tag": tag}, {}
    return t


def test_proxy_forwards_to_first_available():
    clk = FakeClock()
    d = Director(lambda: ["http://a", "http://b"], clock=clk)
    p = HTTPProxy(d, ok_transport("x"))
    st, body, _ = p.handle("GET", "/v2/keys/k")
    assert st == 200 and body["served_by"] == "http://a"


def test_proxy_failover_and_recovery():
    clk = FakeClock()
    d = Director(lambda: ["http://bad", "http://good"],
                 failure_wait=5.0, clock=clk)
    calls = []

    def transport(url, method, path, form):
        calls.append(url)
        if url == "http://bad":
            raise ConnectionError("refused")
        return 200, {"served_by": url}, {}

    p = HTTPProxy(d, transport)
    st, body, _ = p.handle("GET", "/")
    assert body["served_by"] == "http://good"
    # bad endpoint now out of rotation
    calls.clear()
    p.handle("GET", "/")
    assert calls == ["http://good"]
    # after failure_wait it returns
    clk.t += 6
    calls.clear()
    p.handle("GET", "/")
    assert calls[0] == "http://bad"


def test_proxy_zero_endpoints_503():
    d = Director(lambda: [], clock=FakeClock())
    p = HTTPProxy(d, ok_transport("x"))
    st, body, _ = p.handle("GET", "/")
    assert st == 503
    assert "zero endpoints" in body["message"]


def test_proxy_all_endpoints_down_503():
    clk = FakeClock()
    d = Director(lambda: ["http://a"], clock=clk)

    def transport(url, *a):
        raise ConnectionError()

    p = HTTPProxy(d, transport)
    st, body, _ = p.handle("GET", "/")
    assert st == 503


def test_director_refresh_picks_up_new_urls():
    clk = FakeClock()
    urls = ["http://a"]
    d = Director(lambda: list(urls), refresh_interval=30.0, clock=clk)
    assert [e.url for e in d.endpoints()] == ["http://a"]
    urls.append("http://b")
    assert [e.url for e in d.endpoints()] == ["http://a"]  # not yet
    clk.t += 31
    assert [e.url for e in d.endpoints()] == ["http://a", "http://b"]


def test_director_keeps_endpoint_state_across_refresh():
    clk = FakeClock()
    d = Director(lambda: ["http://a", "http://b"],
                 failure_wait=100.0, refresh_interval=1.0, clock=clk)
    d.endpoints()[0].failed(100.0)
    clk.t += 2  # refresh happens, but 'a' stays marked failed
    assert [e.url for e in d.endpoints()] == ["http://b"]


def test_httpproxy_fronts_https_upstream(tmp_path):
    """make_urllib_transport(TLSInfo): the v2 proxy forwards to an
    HTTPS gateway with CA verification (the reference proxy's TLS
    upstream dial); without the CA the endpoint is marked failed."""
    from etcd_tpu.embed import Config, start_etcd
    from etcd_tpu.httpproxy import make_urllib_transport
    from etcd_tpu.transport import TLSInfo

    e = start_etcd(Config(cluster_size=1, data_dir=str(tmp_path / "d"),
                          client_auto_tls=True, auto_tick=False))
    try:
        d = Director(lambda: [e.client_url], 5.0, 30.0)
        tls = TLSInfo(trusted_ca_file=e.client_tls.cert_file)
        p = HTTPProxy(d, make_urllib_transport(tls))
        st, body, _ = p.handle("PUT", "/v2/keys/px/a", {"value": "v"})
        assert st == 201, body
        st, body, _ = p.handle("GET", "/v2/keys/px/a", {})
        assert st == 200 and body["node"]["value"] == "v"
        # no CA: handshake fails, the director marks the endpoint down
        d2 = Director(lambda: [e.client_url], 5.0, 30.0)
        p2 = HTTPProxy(d2, make_urllib_transport(None))
        st, body, _ = p2.handle("GET", "/v2/keys/px/a", {})
        assert st == 503
    finally:
        e.close()
