"""clientv3/ordering parity: a KV wrapper that refuses to serve reads whose
revision regresses below anything this client has already seen.

The reference (client/v3/ordering/kv.go:24-92) records the highest response
revision returned so far; a Get/Txn whose header revision is LOWER than
that means the balancer routed the request to a lagging member, and the
configured ``OrderViolationFunc`` decides what to do — the stock closure
(client/v3/ordering/util.go:27-42) rotates endpoints and gives up with
``ErrNoGreaterRev`` once it has cycled them 5x over.

The TPU-native analog routes serializable reads to explicit members of the
in-process cluster instead of gRPC endpoints: a violation rotates
``member``; linearizable reads (member=None) go through ReadIndex and
cannot regress.
"""
from __future__ import annotations

from etcd_tpu.client import Client


class ErrNoGreaterRev(Exception):
    """No cluster member has a revision >= the previously received one
    (client/v3/ordering/util.go:25)."""


def switch_endpoint_closure(n_members: int):
    """NewOrderViolationSwitchEndpointClosure (util.go:27-42): rotate to
    the next member; fail once every member was cycled 5x."""
    state = {"count": 0}

    def on_violation(kv: "OrderingKV", prev_rev: int) -> None:
        if state["count"] > 5 * n_members:
            raise ErrNoGreaterRev(
                "no cluster members have a revision higher than the "
                f"previously received revision {prev_rev}"
            )
        state["count"] += 1
        kv.member = (kv.member + 1) % n_members

    return on_violation


class OrderingKV:
    """kvOrdering (kv.go:29-92) over the in-process client."""

    def __init__(self, client: Client, member: int = 0,
                 on_violation=None):
        self.c = client
        self.member = member
        self.prev_rev = 0
        self.on_violation = on_violation or switch_endpoint_closure(
            len(client.ec.members)
        )

    def _observe(self, rev: int) -> None:
        if rev > self.prev_rev:
            self.prev_rev = rev

    def get(self, key: bytes, serializable: bool = True, **kw):
        """Get with the revision-monotonicity retry loop (kv.go:53-76).
        Returns the KeyValue (or None), like Client.get."""
        kvs = self.get_range(key, None, serializable, **kw)["kvs"]
        return kvs[0] if kvs else None

    def get_range(self, key: bytes, range_end: bytes | None = None,
                  serializable: bool = True, **kw):
        prev = self.prev_rev
        while True:
            res = self.c.get_range(
                key, range_end, serializable=serializable,
                member=self.member if serializable else None, **kw,
            )
            rev = int(res["header"].revision)
            if rev >= prev:
                self._observe(rev)
                return res
            self.on_violation(self, prev)

    def put(self, key: bytes, value: bytes, **kw):
        res = self.c.put(key, value, **kw)
        self._observe(int(res["rev"]))
        return res

    def delete(self, key: bytes, **kw):
        res = self.c.delete(key, **kw)
        self._observe(int(res["rev"]))
        return res

    def txn(self):
        """Txn passthrough recording the response revision (kv.go:78-92:
        txns are linearized through the leader, so they only ever advance
        prev_rev)."""
        builder = self.c.txn()
        orig_commit = builder.commit

        def commit():
            res = orig_commit()
            self._observe(int(res["rev"]))
            return res

        builder.commit = commit
        return builder
