"""Write-ahead log — segmented, CRC-chained, fsync-disciplined.

Mirrors ``server/storage/wal/wal.go``: append-only segments named
``<seq>-<index>.wal`` holding {metadata, entries, hardstate, snapshot-marker,
crc} records; ``Save`` appends entries+hardstate and fsyncs iff MustSync
(raft/node.go:586-593: vote/term changed or entries non-empty); ``cut`` at
the segment size limit; ``ReadAll`` replays from the last snapshot marker and
truncates a torn tail in place (wal/repair.go). Record payloads here are
pickled host dicts — the device engine's HardState/entry deltas — rather
than protobufs; the framing/CRC layer is walcodec (C++ with Python fallback).
"""
from __future__ import annotations

import os
import pickle

from etcd_tpu.storage.walcodec import (
    HEADER_SIZE,
    first_frame_bytes_needed,
    frame_is_incomplete,
    get_codec,
    tail_chains_cleanly,
)

REC_METADATA = 1
REC_ENTRIES = 2
REC_HARDSTATE = 3
REC_SNAPSHOT = 4  # marker: {index, term} the log is valid from

SEGMENT_BYTES = 8 * 1024 * 1024  # wal.SegmentSizeBytes is 64MB; host-scale 8MB


class WALError(Exception):
    pass


class WAL:
    def __init__(self, dirpath: str, metadata: bytes = b""):
        self.dir = dirpath
        self.codec = get_codec()
        self.crc = 0
        self._f = None
        self.seq = 0
        self.enti = 0  # index of the last entry record appended
        self.metadata = metadata
        os.makedirs(dirpath, exist_ok=True)
        if not self._segments():
            self._cut_to(0, 0, metadata)
        else:
            # opening an existing log: replay to the tail so crc/enti/seq are
            # restored and the last segment is open for append — save()
            # before an explicit read_all() must not write blind (wal.go
            # Open reads to tail before the WAL is appendable)
            self.read_all()

    # -- segments ------------------------------------------------------------
    def _segments(self) -> list[str]:
        return sorted(
            f for f in os.listdir(self.dir) if f.endswith(".wal")
        )

    def _seg_path(self, seq: int, index: int) -> str:
        return os.path.join(self.dir, f"{seq:016x}-{index:016x}.wal")

    def _cut_to(self, seq: int, index: int, metadata: bytes = b"") -> None:
        if self._f:
            self.sync()
            self._f.close()
        self.seq = seq
        path = self._seg_path(seq, index)
        self._f = open(path, "ab")
        # each segment carries an independent crc chain starting at 0 so any
        # segment decodes standalone (the reference instead seeds with a
        # crcType record, wal.go cut; a per-segment chain is equivalent
        # tamper/tear protection with less special-casing)
        self.crc = 0
        if metadata:
            self._append(REC_METADATA, metadata)

    def _maybe_cut(self) -> None:
        if self._f.tell() >= SEGMENT_BYTES:
            # every segment re-carries the metadata record so any suffix of
            # segments replays standalone (wal.go cut writes metadata into
            # each new file)
            self._cut_to(self.seq + 1, self.enti + 1, self.metadata)

    # -- append --------------------------------------------------------------
    def _append(self, rtype: int, payload: bytes) -> None:
        frame, self.crc = self.codec.encode(rtype, payload, self.crc)
        self._f.write(frame)

    def save(self, hardstate: dict | None, entries: list[dict]) -> None:
        """WAL.Save (wal/wal.go): entry records then the hardstate record,
        one fsync for the batch (MustSync rule)."""
        must_sync = bool(entries) or hardstate is not None
        for e in entries:
            self._append(REC_ENTRIES, pickle.dumps(e))
            self.enti = e["index"]
        if hardstate is not None:
            self._append(REC_HARDSTATE, pickle.dumps(hardstate))
        if must_sync:
            self.sync()
        self._maybe_cut()

    def save_snapshot(self, index: int, term: int) -> None:
        self._append(REC_SNAPSHOT, pickle.dumps({"index": index, "term": term}))
        self.sync()

    def sync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        if self._f:
            self.sync()
            self._f.close()
            self._f = None

    # -- replay --------------------------------------------------------------
    def _probe_first_frame(self, seg: str) -> str:
        """Classify a segment by its FIRST frame (each segment carries an
        independent crc chain from 0, so frame one is self-checking; if
        it is broken, nothing after it can be verified either) without
        reading the whole file:

          * ``"valid"``: decodes cleanly — the segment holds records;
          * ``"corrupt"``: the frame is COMPLETE but its crc fails —
            bit rot on durable bytes, never a crash artifact;
          * ``"debris"``: no complete frame — a torn first append.
        """
        path = os.path.join(self.dir, seg)
        with open(path, "rb") as f:
            head = f.read(HEADER_SIZE)
            need = first_frame_bytes_needed(head)
            if need is None or need > os.path.getsize(path):
                return "debris"
            buf = head + f.read(need - len(head))
        if self.codec.decode(buf, 0, 0) is not None:
            return "valid"
        return "corrupt"

    def read_all(self, from_index: int = 0):
        """(metadata, hardstate, entries, snapshot) replay; truncates a torn
        or corrupted final record like wal.openAtTail+repair (repair.go)
        instead of raising. entries are those with
        index > max(from_index, last snapshot marker).

        A torn frame is tolerated at the tail of the LOG, not just the
        last file: a crash inside ``cut`` (or an fsync-lagged filesystem
        dropping a synced-late tail) can leave the torn record in the
        penultimate segment with nothing but unsynced debris after it.
        The repair truncates the torn tail and REMOVES the later
        record-free debris segments. Corruption followed by any segment
        with decodable records is genuinely mid-log and still fails
        loudly — patching it would make a silent hole."""
        metadata = b""
        hardstate: dict | None = None
        snapshot: dict | None = None
        by_index: dict[int, dict] = {}
        crc = 0
        torn = False
        segs = self._segments()
        for si, seg in enumerate(segs):
            if torn:
                break
            path = os.path.join(self.dir, seg)
            with open(path, "rb") as f:
                buf = f.read()
            off = 0
            crc = 0  # per-segment chain
            while off < len(buf):
                hit = self.codec.decode(buf, off, crc)
                if hit is None:
                    debris = segs[si + 1:]
                    probes = {s: self._probe_first_frame(s) for s in debris}
                    if "valid" in probes.values():
                        # records exist PAST the tear: this is mid-log
                        # corruption, not a torn tail; it must not be
                        # patched into a silent hole
                        raise WALError(f"corrupt record mid-log in {seg}")
                    if not frame_is_incomplete(buf, off):
                        # COMPLETE frame, bad crc. In a non-final
                        # segment the bytes were durable (cut() fsyncs
                        # a segment before opening the next), so this
                        # is bit rot, and repairing it would silently
                        # drop fsynced records. In the final segment
                        # the torn-append window CAN leave a junk tail
                        # that happens to parse as a complete frame —
                        # it is rot (refuse) only when what follows the
                        # frame is a self-consistent crc-chained record
                        # run to EOF, i.e. real records stand behind it.
                        end = off + first_frame_bytes_needed(
                            buf[off:off + HEADER_SIZE])
                        if debris or tail_chains_cleanly(buf, end):
                            raise WALError(
                                f"corrupt durable record in {seg} "
                                "(complete frame, crc mismatch)")
                    rotted = [s for s, p in probes.items() if p == "corrupt"]
                    if rotted:
                        # same rule for the segments we would unlink: a
                        # complete-but-crc-broken first frame is bit rot
                        # on durable bytes, not torn-append debris —
                        # removing it would silently delete records
                        raise WALError(
                            f"corrupt durable record in {rotted[0]} "
                            "(complete frame, crc mismatch)")
                    # torn tail: truncate in place, drop record-free
                    # debris segments, stop replay (repair.go)
                    from etcd_tpu.utils.logging import get_logger

                    get_logger().warning(
                        "repaired torn wal tail in %s at offset %d", seg, off
                    )
                    if self._f and not self._f.closed:
                        # the open append handle may point at a debris
                        # segment about to be unlinked
                        self._f.close()
                        self._f = None
                    with open(path, "ab") as f:
                        f.truncate(off)
                    for s in debris:
                        get_logger().warning("dropping torn wal debris %s", s)
                        os.remove(os.path.join(self.dir, s))
                    torn = True
                    break
                consumed, rtype, payload, crc = hit
                off += consumed
                if rtype == REC_METADATA:
                    metadata = payload
                elif rtype == REC_ENTRIES:
                    e = pickle.loads(payload)
                    by_index[e["index"]] = e  # later write wins (truncate+append)
                    for stale in [i for i in by_index if i > e["index"]]:
                        del by_index[stale]
                elif rtype == REC_HARDSTATE:
                    hardstate = pickle.loads(payload)
                elif rtype == REC_SNAPSHOT:
                    snapshot = pickle.loads(payload)
        self.crc = crc
        start = max(
            from_index, snapshot["index"] if snapshot else 0
        )
        entries = [by_index[i] for i in sorted(by_index) if i > start]
        if metadata:
            self.metadata = metadata
        # reopen tail for appending
        if self._f is None or self._f.closed:
            segs = self._segments()
            self.seq = int(segs[-1].split("-")[0], 16)
            self._f = open(os.path.join(self.dir, segs[-1]), "ab")
        if by_index:
            self.enti = max(by_index)
        return metadata, hardstate, entries, snapshot

    def release_to(self, index: int) -> int:
        """Drop whole segments whose entries all precede `index`
        (WAL.ReleaseLockTo after a snapshot). Returns segments removed."""
        segs = self._segments()
        removed = 0
        # a segment is removable if the NEXT segment starts at or before index
        for i in range(len(segs) - 1):
            nxt_start = int(segs[i + 1].split("-")[1].split(".")[0], 16)
            if nxt_start <= index:
                os.remove(os.path.join(self.dir, segs[i]))
                removed += 1
            else:
                break
        return removed
