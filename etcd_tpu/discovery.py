"""Cluster bootstrap via a discovery service — the v2discovery analog.

Re-design of ``server/etcdserver/api/v2discovery/discovery.go``: a new
cluster's members meet at a shared token directory on an existing etcd
(any v2-serving cluster in this framework), each registering
``token/<member-id> = "name=peer-url"`` and waiting until ``size`` (from
``token/_config/size``) members appear, then deriving the identical
initial-cluster string from the first ``size`` registrations sorted by
creation index (discovery.go:160-412).

Blocking waits become poll loops over the clientv2 watcher (this
framework's long-poll convention); a ``wait_hook`` lets a driver
interleave the other members' registrations, standing in for the
concurrent processes of the reference world.
"""
from __future__ import annotations

from typing import Callable

from etcd_tpu import clientv2
from etcd_tpu.clientv2 import KeysAPI
from etcd_tpu.server.v2store import EcodeKeyNotFound, EcodeNodeExist


class DiscoveryError(Exception):
    pass


class ErrSizeNotFound(DiscoveryError):
    """discovery: size key not found"""


class ErrBadSizeKey(DiscoveryError):
    """discovery: size key is bad"""


class ErrDuplicateID(DiscoveryError):
    """discovery: found duplicate id"""


class ErrDuplicateName(DiscoveryError):
    """discovery: found duplicate name"""


class ErrFullCluster(DiscoveryError):
    """discovery: cluster is full"""


class ErrTooManyRetries(DiscoveryError):
    """discovery: too many retries"""


def create_token(keys: KeysAPI, token: str, size: int) -> None:
    """Seed a discovery token the way the public discovery.etcd.io
    /new endpoint does: write token/_config/size."""
    keys.set(f"/{token}/_config/size", str(size))


class Discovery:
    """One member's discovery session (discovery.go discovery struct)."""

    MAX_WAIT_POLLS = 256  # nRetries stand-in for the poll loop

    def __init__(self, keys: KeysAPI, token: str, member_id: int | str,
                 wait_hook: Callable[[], None] | None = None):
        self.c = keys
        self.cluster = token.strip("/")
        self.id = str(member_id)
        # called between empty watch polls — the test-world stand-in for
        # other member processes making progress concurrently
        self.wait_hook = wait_hook

    # -- public (discovery.go:60-90)
    def join_cluster(self, config: str) -> str:
        """JoinCluster: register self, wait for size peers, derive the
        initial-cluster string. `config` is "name=peer-url"."""
        self._check_cluster()  # fast-path full/size errors pre-register
        self._create_self(config)
        nodes, size, index = self._check_cluster()
        all_nodes = self._wait_nodes(nodes, size, index)
        return nodes_to_cluster(all_nodes, size)

    def get_cluster(self) -> str:
        """GetCluster: observer path — no registration."""
        try:
            nodes, size, index = self._check_cluster()
        except ErrFullCluster as e:
            return nodes_to_cluster(e.args[0], e.args[1])
        all_nodes = self._wait_nodes(nodes, size, index)
        return nodes_to_cluster(all_nodes, size)

    # -- internals
    def _self_key(self) -> str:
        return f"/{self.cluster}/{self.id}"

    def _create_self(self, contents: str) -> None:
        # discovery.go:203-218: Create fails NodeExist -> duplicate id
        try:
            self.c.create(self._self_key(), contents)
        except clientv2.Error as e:
            if e.code == EcodeNodeExist:
                raise ErrDuplicateID() from None
            raise

    def _check_cluster(self):
        # discovery.go:220-287
        try:
            resp = self.c.get(f"/{self.cluster}/_config/size")
        except clientv2.Error as e:
            if e.code == EcodeKeyNotFound:
                raise ErrSizeNotFound() from None
            raise
        try:
            size = int(resp.node["value"])
            if size <= 0:
                raise ValueError
        except (ValueError, TypeError):
            raise ErrBadSizeKey() from None

        resp = self.c.get(f"/{self.cluster}")
        nodes = [n for n in resp.node.get("nodes", [])
                 if not n["key"].rsplit("/", 1)[-1].startswith("_")]
        nodes.sort(key=lambda n: n["createdIndex"])
        # find self among the first `size` registrants
        for i, n in enumerate(nodes):
            if n["key"].rsplit("/", 1)[-1] == self.id:
                break
            if i >= size - 1:
                raise ErrFullCluster(nodes[:size], size)
        return nodes, size, resp.index

    def _wait_nodes(self, nodes: list, size: int, index: int) -> list:
        # discovery.go:326-383: watch the token dir until size appear
        if len(nodes) > size:
            nodes = nodes[:size]
        all_nodes = list(nodes)
        w = self.c.watcher(f"/{self.cluster}", after_index=index,
                           recursive=True)
        polls = 0
        while len(all_nodes) < size:
            ev = w.next()
            if ev is None:
                polls += 1
                if polls > self.MAX_WAIT_POLLS:
                    raise ErrTooManyRetries()
                if self.wait_hook is not None:
                    self.wait_hook()
                continue
            name = ev.node["key"].rsplit("/", 1)[-1]
            if name.startswith("_"):
                continue
            all_nodes.append(ev.node)
        return all_nodes


def nodes_to_cluster(nodes: list, size: int) -> str:
    """discovery.go:390-406: join registrations into the initial-cluster
    string; names must be unique."""
    us = ",".join(n["value"] for n in nodes)
    names = set()
    for part in us.split(","):
        name = part.split("=", 1)[0]
        if name in names:
            raise ErrDuplicateName(us)
        names.add(name)
    if len(us.split(",")) != size:
        raise ErrDuplicateName(us)
    return us
