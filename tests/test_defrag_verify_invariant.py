"""Shared compaction invariant between `etcdutl defrag` and the offline
verifier (VERDICT r2 weak #8): defragmenting a data dir must preserve
exactly what verify checks — per-member revision + KV hash, and the
cross-member equal-revision => equal-hash property — while shrinking or
keeping the file size (stale records dropped).

Reference: defrag is a backend rewrite (etcdutl defrag -> backend.Defrag,
server/storage/backend/backend.go:436-490) that bbolt guarantees is
content-preserving; the offline checker is etcdutl snapshot status /
hashkv over the same files.
"""
from __future__ import annotations

import os

import pytest

from etcd_tpu import verify
from etcd_tpu.client import Client
from etcd_tpu.embed import Config, start_etcd


@pytest.fixture()
def data_dir(tmp_path):
    e = start_etcd(Config(data_dir=str(tmp_path / "d"), auto_tick=False))
    cl = Client(e.server)
    for i in range(20):
        cl.put(b"k%d" % (i % 5), b"v%d" % i)  # overwrites -> stale records
    cl.delete(b"k0")
    cl.compact(int(cl.get_range(b"k1")["header"].revision) - 3)
    e.close()
    return str(tmp_path / "d")


def test_defrag_preserves_verify_reports(data_dir):
    from etcd_tpu import etcdutl

    before = verify.verify_data_dir(data_dir)  # raises VerifyError on rot
    assert all(r["hash"] is not None for r in before), before
    sizes_before = {
        p: os.path.getsize(os.path.join(data_dir, p))
        for p in os.listdir(data_dir)
    }
    assert etcdutl.main(["defrag", "--data-dir", data_dir]) == 0
    after = verify.verify_data_dir(data_dir)
    # the invariant: defrag changes no consistent index, revision or hash
    assert [
        (r["consistent_index"], r["revision"], r["hash"]) for r in before
    ] == [
        (r["consistent_index"], r["revision"], r["hash"]) for r in after
    ]
    # and only ever shrinks the files (stale records dropped)
    for p, sz in sizes_before.items():
        assert os.path.getsize(os.path.join(data_dir, p)) <= sz
