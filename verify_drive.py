"""End-to-end verify drive (see .claude/skills/verify): library surface +
this round's changed paths (leasing cache-miss put, degenerate auth grants,
padded-lane stabilize, scan-only round program)."""
import os

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)

from etcd_tpu.embed import EtcdCluster
from etcd_tpu.client import Client, Op
from etcd_tpu.concurrency import Mutex, Election, Session
from etcd_tpu.leasing import LeasingKV

ec = EtcdCluster(n_members=3)
c = Client(ec)

# KV + watch + lease
c.put(b"k1", b"v1")
assert c.get(b"k1").value == b"v1"
w = c.watch_prefix(b"k")
c.put(b"k2", b"v2")
evs = w.events()
assert any(e.kv.key == b"k2" for e in evs), evs
lid = c.lease_grant(777, 60)
c.put(b"lk", b"lv", lease=777)
assert c.get(b"lk") is not None

# concurrency
s = Session(c, ttl=60)
m = Mutex(s, b"mu")
m.lock()
m.unlock()
e = Election(s, b"el")
e.campaign(b"leader-a")
assert e.leader().value == b"leader-a"

# leasing: the ADVICE-medium path — txn() invalidates the cache entry for an
# owned pre-existing key; the next owned put must NOT fabricate
# create_revision/version=1, and the next get must serve the true ones
lkv = LeasingKV(c, b"_lease")
lkv.put(b"key-x", b"v0")          # not owned yet -> plain put
kv0 = lkv.get(b"key-x")           # acquires ownership + caches
assert kv0.value == b"v0"
create0, ver0 = kv0.create_revision, kv0.version
lkv.txn().then(Op("put", b"key-x", b"v1")).commit()  # invalidates cache
res = lkv.put(b"key-x", b"v2")    # owned put on unknown cache entry
kv2 = lkv.get(b"key-x")
assert kv2.value == b"v2", kv2
assert kv2.create_revision == create0, (kv2.create_revision, create0)
assert kv2.version > ver0, (kv2.version, ver0)
print("leasing cache-miss put: create_revision preserved "
      f"({create0} -> {kv2.create_revision}), version {ver0} -> {kv2.version}")

# auth: degenerate stored grant must not break authz; degenerate request
# range must deny, not raise ValueError
from etcd_tpu.server.auth import AuthStore, ErrPermissionDenied, Permission, READ
au = AuthStore()
au.user_add("root", "pw")
au.role_add("root")
au.user_grant_role("root", "root")
au.user_add("alice", "pw")
au.role_add("r1")
au.role_grant_permission("r1", Permission(READ, b"b", b"a"))  # degenerate
au.role_grant_permission("r1", Permission(READ, b"k", b"l"))  # real
au.user_grant_role("alice", "r1")
au.auth_enable()
au.check_user("alice", b"k")                        # real grant still works
try:
    au.check_user("alice", b"z", b"a")              # degenerate request
    raise SystemExit("degenerate request range was ALLOWED")
except ErrPermissionDenied:
    pass
print("auth degenerate grant/request: denied cleanly, real grants intact")

# faults + corruption check
lead = next(m for m in range(3) if ec.cl.leader() == m)
follower = (lead + 1) % 3
ec.cl.isolate(follower)   # quorum of 2 keeps committing
c.put(b"k3", b"v3")
assert c.get(b"k3").value == b"v3"
ec.cl.recover()
for _ in range(8):
    ec.cl.step(tick=True)
ec.corruption_check()
print("fault + corruption check OK")

# cluster version negotiation + downgrade (round 4): mixed-version fleet
# settles on the min; a downgrade job runs enable -> binary swap ->
# version drop -> auto-cancel
def settle(n=6):
    for _ in range(n):
        ec.cl.step(); ec._pump()
ec.set_server_version(1, "3.5.7")
assert ec.monitor_versions() == "3.5.0"
settle()
ec.set_server_version(1, "3.6.0")
assert ec.monitor_versions() == "3.6.0"
settle()
ec.downgrade("enable", "3.5.0")
settle()
for m in range(3):
    ec.set_server_version(m, "3.5.2")
assert ec.monitor_versions() == "3.5.0"
settle()
assert ec.monitor_downgrade() is True
settle()
assert not any(ms.downgrade.enabled for ms in ec.members)
print("version negotiation + downgrade job: 3.6.0 -> 3.5.0 -> job cancelled")

# padded-lane stabilize: a 3-lane fleet pads to 16; stabilize must converge
# (padding lanes untic­ked) and see real-lane traffic only
from etcd_tpu.harness.cluster import Cluster
cl = Cluster(3, C=3)
for i in range(3):
    cl.campaign(0, c=i)
cl.stabilize()
assert all(cl.leader(c) == 0 for c in range(3))
cl.tick(12)  # ticks only real lanes now
assert cl._pending() == 0 or cl.stabilize() is cl
print("padded-lane harness OK (leaders:", [cl.leader(c) for c in range(3)], ")")

print("VERIFY DRIVE PASSED")
