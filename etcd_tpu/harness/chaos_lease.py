"""Lease stress + expiry checking under faults — the host-layer chaos tier.

The reference's functional tester stresses leases while faults fire
(tests/functional/tester/stresser_lease.go: create leases with and without
keepalives, attach keys) and then checks expiry semantics
(tester/checker_lease_expire.go + checker_short_ttl_lease_expire.go):
after waiting out the TTL, every lease that was NOT kept alive must be
gone — with its attached keys deleted — and every kept-alive lease must
survive with its keys intact. The device chaos tier (harness/chaos.py)
covers raft safety at fleet scale; this tier drives the HOST layer
(Lessor, revoke-through-consensus, MVCC deletes) through the same fault
classes via the keep-mask, which nothing exercised before.

Faults make individual requests fail (no leader / timeout) — like the
reference tester, the stresser tolerates errors during fault epochs and
the checker runs after heal, within a bounded slack (the checker's own
retry loop, checker_lease_expire.go waitForLeaseExpire)."""
from __future__ import annotations

import numpy as np

from etcd_tpu.server.kvserver import EtcdCluster, ServerError
from etcd_tpu.server.lease import ErrLeaseNotFound, LeaseError


class _Rng:
    def __init__(self, seed: int):
        self.r = np.random.default_rng(seed)

    def keep_mask(self, M: int, drop_p: float) -> np.ndarray:
        km = self.r.random((M, M, 1)) >= drop_p
        return km | np.eye(M, dtype=bool)[:, :, None]


def run_lease_chaos(
    n_members: int = 5,
    n_leases: int = 8,
    # like the reference's stress leases, the kept TTL is LONG relative
    # to a fault window (stresser_lease.go TTL=120s vs second-scale
    # blips): a multi-round partition must not push every kept lease
    # into legal-expiry territory, or the checker verifies nothing
    ttl: int = 8,
    short_ttl: int = 1,
    fault_rounds: int = 30,
    drop_p: float = 0.25,
    seed: int = 0,
    retries: int = 3,
) -> dict:
    """One stress/fault/heal/check cycle. Returns counters; the caller
    asserts on ``violations`` AND ``lease_gate_failures`` (chaos_run.py
    folds both into its JSON).

    Leases [0, n//2) are kept alive through the fault epoch; leases
    [n//2, n), one short-TTL lease, and a short-TTL lease granted
    MID-EPOCH (the checker_short_ttl_lease_expire.go case — born under
    faults, must still expire) are abandoned and must expire with their
    keys revoked. TTLs are seconds = lease-clock ticks here.

    Gates (the reference checker's bar, r4 verdict Weak #3): the
    stresser retries each keepalive up to `retries` times, and the run
    FAILS if logical request failures exceed 20% of attempts or if more
    than ONE kept lease lands in the indeterminate bucket — a lease
    tier that mostly errors under faults and excuses itself through
    indeterminacy proves nothing."""
    import jax.numpy as jnp

    ec = EtcdCluster(n_members=n_members, lease_min_ttl=1)
    ec.ensure_leader()
    rng = _Rng(seed)
    M = ec.M

    kept = list(range(1, n_leases // 2 + 1))
    abandoned = list(range(n_leases // 2 + 1, n_leases + 1))
    for lid in kept + abandoned:
        ec.lease_grant(lid, ttl)
        ec.put(b"lease-k-%d" % lid, b"v", lease=lid)
    short_id = n_leases + 1
    ec.lease_grant(short_id, short_ttl)  # checker_short_ttl analog
    ec.put(b"lease-k-%d" % short_id, b"v", lease=short_id)
    mid_short_id = n_leases + 2  # granted mid-epoch, under faults

    attempts = 0
    failures = 0
    tick_errors = 0
    keepalive_ok = 0
    mid_short_granted = False
    mid_short_tries = 0
    # a kept lease whose renewals gapped >= TTL during the fault epoch may
    # legally expire — the stresser failed, not the system. The reference
    # checker likewise only asserts on leases its stresser could service.
    last_renew = {lid: 0 for lid in kept}
    indeterminate: set[int] = set()
    # RETRY POLICY: the per-round keep mask freezes the fault pattern for
    # a whole round, so retrying within one round faces the identical
    # partition and proves nothing. Instead the stresser renews EVERY
    # round (the reference stresser retries continuously as real time
    # passes) and a LOGICAL failure is `retries` consecutive failed
    # rounds — sustained unavailability, not one unlucky mask.
    consec = {lid: 0 for lid in kept}

    def renew_all(r: int) -> None:
        nonlocal attempts, failures, keepalive_ok
        for lid in kept:
            try:
                ec.lease_keepalive(lid)
            except ErrLeaseNotFound:
                # the lease legally expired during a renewal gap and
                # the expiry loop already revoked it: exactly the
                # indeterminate case, not a crash
                indeterminate.add(lid)
                continue
            except (ServerError, LeaseError):
                consec[lid] += 1
                if consec[lid] >= retries:
                    attempts += 1
                    failures += 1
                    consec[lid] = 0
                # a lease is only unverifiable once its RENEWAL GAP
                # reached expiry range — a failed round with a fresh
                # renewal behind it proves nothing about expiry
                if r - last_renew[lid] >= ttl - 1:
                    indeterminate.add(lid)
            else:
                attempts += 1
                keepalive_ok += 1
                consec[lid] = 0
                last_renew[lid] = r

    # fault epoch: random link drops re-rolled every round while the lease
    # clock advances and keepalives fight through the faults
    for r in range(fault_rounds):
        ec.cl.eng.keep_mask = jnp.asarray(rng.keep_mask(M, drop_p))
        try:
            ec.tick(lease_clock=True)
        except ServerError:
            tick_errors += 1
        renew_all(r)
        if r >= fault_rounds // 2 and not mid_short_granted and \
                mid_short_tries < retries:
            # short-TTL lease born in the middle of the fault epoch:
            # it must expire like any other once abandoned
            mid_short_tries += 1
            try:
                try:
                    ec.lease_grant(mid_short_id, short_ttl)
                except LeaseError:
                    # ErrLeaseExists: the previous try's grant DID
                    # commit (its _propose merely timed out under
                    # faults) — that IS success, continue to the put
                    pass
                ec.put(b"lease-k-%d" % mid_short_id, b"v",
                       lease=mid_short_id)
                mid_short_granted = True
                attempts += 1
            except (ServerError, LeaseError):
                if mid_short_tries >= retries:
                    attempts += 1
                    failures += 1

    # heal, then give expiry the reference checker's slack: revokes that
    # queued behind faults drain through consensus here. The stresser
    # KEEPS renewing the kept set through the wait (the wait exists to
    # expire the ABANDONED set; without renewals the kept leases would
    # legitimately expire too and prove nothing).
    ec.cl.recover()
    for r in range(ttl + 6):
        try:
            ec.tick(lease_clock=True)
        except ServerError:
            tick_errors += 1
        renew_all(fault_rounds + r)

    violations: list[str] = []
    lead = ec.ensure_leader()
    live = set(ec.leases())
    for lid in kept:
        if lid in indeterminate:
            continue  # renewals gapped past TTL: expiry would be legal
        # kept alive through the epoch, so renewed within TTL: must live
        if lid not in live:
            violations.append(f"kept lease {lid} expired")
        elif ec.range(b"lease-k-%d" % lid)["count"] != 1:
            violations.append(f"kept lease {lid} lost its key")
    expired_set = abandoned + [short_id] + (
        [mid_short_id] if mid_short_granted else [])
    for lid in expired_set:
        if lid in live:
            violations.append(f"abandoned lease {lid} still alive")
        elif ec.range(b"lease-k-%d" % lid)["count"] != 0:
            violations.append(f"expired lease {lid} left its key behind")

    # ---- gates (fail the run, don't excuse it)
    gate_failures: list[str] = []
    if len(indeterminate) > 1:
        gate_failures.append(
            f"indeterminate bucket too large: {len(indeterminate)}/"
            f"{len(kept)} kept leases unverifiable (max 1)")
    if attempts and failures > 0.2 * attempts:
        gate_failures.append(
            f"request failure rate {failures}/{attempts} exceeds 20% "
            f"despite {retries} retries per request")

    return {
        "lease_kept": len(kept),
        "lease_kept_indeterminate": len(indeterminate),
        "lease_abandoned": len(expired_set),
        "lease_mid_epoch_short_granted": mid_short_granted,
        "lease_keepalives_ok": keepalive_ok,
        "lease_attempts": attempts,
        "lease_request_failures": failures,
        "lease_tick_errors": tick_errors,
        "lease_violations": violations,
        "lease_gate_failures": gate_failures,
        "leader_after_heal": lead,
    }


def run_runner_chaos(
    n_members: int = 3,
    n_runners: int = 3,
    fault_rounds: int = 20,
    drop_p: float = 0.2,
    seed: int = 1,
) -> dict:
    """Election-runner stress under faults (tester/stresser_runner.go,
    which shells out to functional/runner's election-command): N
    concurrency.Election candidates campaign/proclaim/resign against a
    faulted cluster; mutual exclusion (never two holders at once) must
    hold throughout, and after heal the election must make progress."""
    import jax.numpy as jnp

    from etcd_tpu.client import Client
    from etcd_tpu.concurrency import ConcurrencyError, Election, Session

    ec = EtcdCluster(n_members=n_members, lease_min_ttl=1)
    ec.ensure_leader()
    c = Client(ec)
    rng = _Rng(seed)
    sessions = [Session(c, ttl=60) for _ in range(n_runners)]
    els = [Election(s, b"chaos-el") for s in sessions]

    errors = 0
    exclusion_violations = 0
    leaders_seen: set[bytes] = set()
    for r in range(fault_rounds):
        ec.cl.eng.keep_mask = jnp.asarray(rng.keep_mask(ec.M, drop_p))
        i = r % n_runners
        try:
            if els[i].is_leader():
                els[i].proclaim(b"v%d" % r)
                els[i].resign()
            else:
                els[i].campaign(b"runner-%d" % i, max_rounds=30)
        except (ServerError, ConcurrencyError):
            errors += 1
        # mutual exclusion: by construction at most one lowest
        # create-revision key exists; violation = two runners both
        # believing they hold it. The observation itself is a
        # linearizable read and may time out mid-fault — skip that
        # round's check, as the reference checker retries around
        # cluster unavailability.
        try:
            holders = [
                j for j, e in enumerate(els) if e.my_rev and e.is_leader()
            ]
            if len(holders) > 1:
                exclusion_violations += 1
            lv = els[i].leader()
            if lv is not None:
                leaders_seen.add(bytes(lv.value))
        except (ServerError, ConcurrencyError):
            errors += 1
    ec.cl.recover()
    # post-heal progress: someone can win an election cleanly
    for e in els:
        try:
            e.resign()
        except (ServerError, ConcurrencyError):
            pass
    els[0].campaign(b"final", max_rounds=200)
    final_ok = els[0].is_leader()
    return {
        "runner_count": n_runners,
        "runner_errors": errors,
        "runner_exclusion_violations": exclusion_violations,
        "runner_leaders_seen": len(leaders_seen),
        "runner_final_progress": bool(final_ok),
    }


def main(argv=None) -> int:
    """CLI: run both host tiers and print ONE JSON line. chaos_run.py
    invokes this in a CPU subprocess — the tiers are host-layer tests
    whose EtcdCluster steps would otherwise run C=1 device programs over
    the TPU tunnel at ~3.5s per op."""
    import argparse
    import json

    p = argparse.ArgumentParser()
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    rep = run_lease_chaos(seed=args.seed)
    rep.update(run_runner_chaos(seed=args.seed))
    print(json.dumps(rep))
    return 0


if __name__ == "__main__":
    import sys

    from etcd_tpu.utils.cache import entrypoint_platform_setup

    entrypoint_platform_setup(force_cpu=True)
    sys.exit(main())
