"""Replay ALL reference interaction goldens (raft/testdata/*.txt) against
the TPU engine through the InteractionEnv command language
(raft/rafttest/interaction_env_handler.go:29-146, interaction_test.go:34).

Comparison is EXACT: every line — structural output (Ready blocks,
message lines, entries, status, raft-log) AND every logger line (role
transitions, vote casting/tallies, append rejections, log-conflict
resolution, probe/snapshot pause-resume bookkeeping, joint-config
transitions) — must match the golden verbatim, modulo whitespace runs
and one deliberate equivalence: bare "ok" and "ok (quiet)"
acknowledgement lines both normalize away, since they differ only in
whether a suppressed-logger line existed while output was off.
"""
from __future__ import annotations

import os
import re

import pytest

from etcd_tpu.harness.datadriven import parse_file, reference_available, testdata
from etcd_tpu.harness.interaction import InteractionEnv

GOLDENS = [
    "campaign.txt",
    "campaign_learner_must_vote.txt",
    "confchange_v1_add_single.txt",
    "confchange_v1_remove_leader.txt",
    "confchange_v2_add_double_auto.txt",
    "confchange_v2_add_double_implicit.txt",
    "confchange_v2_add_single_auto.txt",
    "confchange_v2_add_single_explicit.txt",
    "probe_and_replicate.txt",
    "snapshot_succeed_via_app_resp.txt",
]


def normalize(text: str) -> list[str]:
    lines: list[str] = []
    for raw in text.split("\n"):
        line = raw.strip()
        if not line or line in ("ok", "ok (quiet)"):
            # bare acknowledgements carry no semantic content; the quiet
            # variants differ only in whether any suppressed line existed
            continue
        lines.append(re.sub(r"\s+", " ", line))
    return lines


@pytest.mark.skipif(not reference_available(), reason="no reference checkout")
@pytest.mark.parametrize("fname", GOLDENS)
def test_interaction_golden(fname):
    env = InteractionEnv()
    for case in parse_file(testdata("testdata", fname)):
        out = env.handle(case)
        exp = "\n".join(case.expected)
        got, want = normalize(out), normalize(exp)
        assert got == want, (
            f"{fname}:{case.line} ({case.cmd} {case.args})\n"
            f"-- expected --\n{exp}\n-- actual --\n{out}"
        )
