"""Lease stress + expiry checking under faults — the host-layer chaos tier.

The reference's functional tester stresses leases while faults fire
(tests/functional/tester/stresser_lease.go: create leases with and without
keepalives, attach keys) and then checks expiry semantics
(tester/checker_lease_expire.go + checker_short_ttl_lease_expire.go):
after waiting out the TTL, every lease that was NOT kept alive must be
gone — with its attached keys deleted — and every kept-alive lease must
survive with its keys intact. The device chaos tier (harness/chaos.py)
covers raft safety at fleet scale; this tier drives the HOST layer
(Lessor, revoke-through-consensus, MVCC deletes) through the same fault
classes via the keep-mask, which nothing exercised before.

Faults make individual requests fail (no leader / timeout) — like the
reference tester, the stresser tolerates errors during fault epochs and
the checker runs after heal, within a bounded slack (the checker's own
retry loop, checker_lease_expire.go waitForLeaseExpire)."""
from __future__ import annotations

import numpy as np

from etcd_tpu.server.kvserver import EtcdCluster, ServerError


class _Rng:
    def __init__(self, seed: int):
        self.r = np.random.default_rng(seed)

    def keep_mask(self, M: int, drop_p: float) -> np.ndarray:
        km = self.r.random((M, M, 1)) >= drop_p
        return km | np.eye(M, dtype=bool)[:, :, None]


def run_lease_chaos(
    n_members: int = 5,
    n_leases: int = 8,
    ttl: int = 4,
    short_ttl: int = 1,
    fault_rounds: int = 30,
    drop_p: float = 0.25,
    seed: int = 0,
) -> dict:
    """One stress/fault/heal/check cycle. Returns counters; the caller
    asserts on ``violations`` (and chaos_run.py folds them into its JSON).

    Leases [0, n//2) are kept alive through the fault epoch; leases
    [n//2, n) and one short-TTL lease are abandoned and must expire with
    their keys revoked. TTLs are seconds = lease-clock ticks here."""
    import jax.numpy as jnp

    ec = EtcdCluster(n_members=n_members, lease_min_ttl=1)
    ec.ensure_leader()
    rng = _Rng(seed)
    M = ec.M

    kept = list(range(1, n_leases // 2 + 1))
    abandoned = list(range(n_leases // 2 + 1, n_leases + 1))
    for lid in kept + abandoned:
        ec.lease_grant(lid, ttl)
        ec.put(b"lease-k-%d" % lid, b"v", lease=lid)
    short_id = n_leases + 1
    ec.lease_grant(short_id, short_ttl)  # checker_short_ttl analog
    ec.put(b"lease-k-%d" % short_id, b"v", lease=short_id)

    errors = 0
    keepalive_ok = 0
    # a kept lease whose renewals gapped >= TTL during the fault epoch may
    # legally expire — the stresser failed, not the system. The reference
    # checker likewise only asserts on leases its stresser could service.
    last_renew = {lid: 0 for lid in kept}
    indeterminate: set[int] = set()
    # fault epoch: random link drops re-rolled every round while the lease
    # clock advances and keepalives fight through the faults
    for r in range(fault_rounds):
        ec.cl.eng.keep_mask = jnp.asarray(rng.keep_mask(M, drop_p))
        try:
            ec.tick(lease_clock=True)
        except ServerError:
            errors += 1
        if r % 2 == 0:
            for lid in kept:
                try:
                    ec.lease_keepalive(lid)
                    keepalive_ok += 1
                    last_renew[lid] = r
                except ServerError:
                    errors += 1
                    if r - last_renew[lid] >= ttl - 1:
                        indeterminate.add(lid)

    # heal, then give expiry the reference checker's slack: revokes that
    # queued behind faults drain through consensus here. The stresser
    # KEEPS renewing the kept set through the wait (the wait exists to
    # expire the ABANDONED set; without renewals the kept leases would
    # legitimately expire too and prove nothing).
    ec.cl.recover()
    for r in range(ttl + 6):
        try:
            ec.tick(lease_clock=True)
        except ServerError:
            errors += 1
        if r % 2 == 0:
            for lid in kept:
                try:
                    ec.lease_keepalive(lid)
                except ServerError:
                    errors += 1
                    indeterminate.add(lid)

    violations: list[str] = []
    lead = ec.ensure_leader()
    live = set(ec.leases())
    for lid in kept:
        if lid in indeterminate:
            continue  # renewals gapped past TTL: expiry would be legal
        # kept alive through the epoch, so renewed within TTL: must live
        if lid not in live:
            violations.append(f"kept lease {lid} expired")
        elif ec.range(b"lease-k-%d" % lid)["count"] != 1:
            violations.append(f"kept lease {lid} lost its key")
    for lid in abandoned + [short_id]:
        if lid in live:
            violations.append(f"abandoned lease {lid} still alive")
        elif ec.range(b"lease-k-%d" % lid)["count"] != 0:
            violations.append(f"expired lease {lid} left its key behind")

    return {
        "lease_kept": len(kept),
        "lease_kept_indeterminate": len(indeterminate),
        "lease_abandoned": len(abandoned) + 1,
        "lease_keepalives_ok": keepalive_ok,
        "lease_request_errors": errors,
        "lease_violations": violations,
        "leader_after_heal": lead,
    }


def run_runner_chaos(
    n_members: int = 3,
    n_runners: int = 3,
    fault_rounds: int = 20,
    drop_p: float = 0.2,
    seed: int = 1,
) -> dict:
    """Election-runner stress under faults (tester/stresser_runner.go,
    which shells out to functional/runner's election-command): N
    concurrency.Election candidates campaign/proclaim/resign against a
    faulted cluster; mutual exclusion (never two holders at once) must
    hold throughout, and after heal the election must make progress."""
    import jax.numpy as jnp

    from etcd_tpu.client import Client
    from etcd_tpu.concurrency import ConcurrencyError, Election, Session

    ec = EtcdCluster(n_members=n_members, lease_min_ttl=1)
    ec.ensure_leader()
    c = Client(ec)
    rng = _Rng(seed)
    sessions = [Session(c, ttl=60) for _ in range(n_runners)]
    els = [Election(s, b"chaos-el") for s in sessions]

    errors = 0
    exclusion_violations = 0
    leaders_seen: set[bytes] = set()
    for r in range(fault_rounds):
        ec.cl.eng.keep_mask = jnp.asarray(rng.keep_mask(ec.M, drop_p))
        i = r % n_runners
        try:
            if els[i].is_leader():
                els[i].proclaim(b"v%d" % r)
                els[i].resign()
            else:
                els[i].campaign(b"runner-%d" % i, max_rounds=30)
        except (ServerError, ConcurrencyError):
            errors += 1
        # mutual exclusion: by construction at most one lowest
        # create-revision key exists; violation = two runners both
        # believing they hold it. The observation itself is a
        # linearizable read and may time out mid-fault — skip that
        # round's check, as the reference checker retries around
        # cluster unavailability.
        try:
            holders = [
                j for j, e in enumerate(els) if e.my_rev and e.is_leader()
            ]
            if len(holders) > 1:
                exclusion_violations += 1
            lv = els[i].leader()
            if lv is not None:
                leaders_seen.add(bytes(lv.value))
        except (ServerError, ConcurrencyError):
            errors += 1
    ec.cl.recover()
    # post-heal progress: someone can win an election cleanly
    for e in els:
        try:
            e.resign()
        except (ServerError, ConcurrencyError):
            pass
    els[0].campaign(b"final", max_rounds=200)
    final_ok = els[0].is_leader()
    return {
        "runner_count": n_runners,
        "runner_errors": errors,
        "runner_exclusion_violations": exclusion_violations,
        "runner_leaders_seen": len(leaders_seen),
        "runner_final_progress": bool(final_ok),
    }


def main(argv=None) -> int:
    """CLI: run both host tiers and print ONE JSON line. chaos_run.py
    invokes this in a CPU subprocess — the tiers are host-layer tests
    whose EtcdCluster steps would otherwise run C=1 device programs over
    the TPU tunnel at ~3.5s per op."""
    import argparse
    import json

    p = argparse.ArgumentParser()
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    rep = run_lease_chaos(seed=args.seed)
    rep.update(run_runner_chaos(seed=args.seed))
    print(json.dumps(rep))
    return 0


if __name__ == "__main__":
    import os
    import sys

    # force CPU before jax initialises (the sitecustomize pins the axon
    # TPU platform otherwise)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from etcd_tpu.utils.cache import configure_compile_cache

    configure_compile_cache()
    sys.exit(main())
