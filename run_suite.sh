#!/bin/bash
# Full-suite run with wall-clock + RSS telemetry (single-core VM: run alone).
cd /root/repo
T0=$(date +%s)
python -m pytest tests/ -q > suite_run.log 2>&1 &
PYT=$!
( while kill -0 $PYT 2>/dev/null; do
    ps -o rss= -p $PYT
    sleep 15
  done ) > suite_rss.log 2>/dev/null &
wait $PYT
RC=$?
echo "WALL_SECONDS=$(( $(date +%s) - T0 )) RC=$RC" >> suite_run.log
exit $RC
