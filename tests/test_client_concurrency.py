"""Client façade + coordination recipes — clientv3 and clientv3/concurrency
parity (Mutex per mutex.go, Election per election.go, STM per stm.go,
namespacing per client/v3/namespace)."""
import pytest

from etcd_tpu.client import Client, prefix_range_end
from etcd_tpu.concurrency import STM, Election, Mutex, Session
from etcd_tpu.server.kvserver import EtcdCluster, Op


@pytest.fixture(scope="module")
def cli():
    ec = EtcdCluster(n_members=3)
    ec.ensure_leader()
    return Client(ec)


def test_prefix_range_end():
    assert prefix_range_end(b"abc") == b"abd"
    assert prefix_range_end(b"a\xff") == b"b"
    assert prefix_range_end(b"\xff\xff") == b"\x00"


def test_kv_roundtrip_and_txn_builder(cli):
    cli.put(b"cfoo", b"1")
    assert cli.get(b"cfoo").value == b"1"
    res = (
        cli.txn()
        .if_(cli.compare_value(b"cfoo", "=", b"1"))
        .then(Op("put", b"cfoo", b"2"))
        .else_(Op("delete", b"cfoo"))
        .commit()
    )
    assert res["succeeded"] and cli.get(b"cfoo").value == b"2"
    cli.delete(b"cfoo")
    assert cli.get(b"cfoo") is None


def test_namespace_isolation(cli):
    a = Client(cli.ec, namespace=b"app-a/")
    b = Client(cli.ec, namespace=b"app-b/")
    a.put(b"k", b"A")
    b.put(b"k", b"B")
    assert a.get(b"k").value == b"A"
    assert b.get(b"k").value == b"B"
    assert a.get_prefix(b"")["count"] == 1
    # raw view sees both, namespaced
    raw = cli.get_range(b"app-", b"app.")
    assert {kv.key for kv in raw["kvs"]} == {b"app-a/k", b"app-b/k"}


def test_watch_via_client(cli):
    w = cli.watch_prefix(b"wc/")
    cli.put(b"wc/1", b"x")
    cli.delete(b"wc/1")
    evs = w.events()
    assert [(e.type, e.kv.key) for e in evs] == [("put", b"wc/1"), ("delete", b"wc/1")]
    assert w.cancel()


def test_mutex_exclusion(cli):
    s1, s2 = Session(cli), Session(cli)
    m1, m2 = Mutex(s1, b"locks/x"), Mutex(s2, b"locks/x")
    m1.lock()
    assert m1.is_owner()
    assert not m2.try_lock()  # held by m1
    m1.unlock()
    m2.lock()
    assert m2.is_owner() and not m1.is_owner()
    m2.unlock()
    s1.close()
    s2.close()


def test_mutex_released_by_session_expiry(cli):
    s1 = Session(cli, ttl=3)
    m1 = Mutex(s1, b"locks/y")
    m1.lock()
    s2 = Session(cli, ttl=60)
    m2 = Mutex(s2, b"locks/y")
    assert not m2.try_lock()
    # s1's lease expires (no keepalive) -> key deleted -> m2 acquires.
    # The lock-wait loop deliberately does NOT advance the lease clock
    # (that would fast-forward every session's TTL), so pass time here.
    for _ in range(5):
        cli.ec.tick()
    m2.lock(max_rounds=30)
    assert m2.is_owner()
    m2.unlock()
    s2.close()


def test_election_campaign_proclaim_resign(cli):
    s1, s2 = Session(cli), Session(cli)
    e1, e2 = Election(s1, b"elect/z"), Election(s2, b"elect/z")
    e1.campaign(b"v1")
    assert e1.is_leader()
    assert e1.leader().value == b"v1"
    e1.proclaim(b"v1.1")
    assert e1.leader().value == b"v1.1"
    # e2 waits; e1 resigns; e2 takes over
    import etcd_tpu.concurrency as conc

    with pytest.raises(conc.ConcurrencyError):
        e2.campaign(b"v2", max_rounds=3)  # can't win while e1 holds it
    e1.resign()
    e2.campaign(b"v2")
    assert e2.is_leader() and e2.leader().value == b"v2"
    e2.resign()
    s1.close()
    s2.close()


def test_stm_transfer(cli):
    cli.put(b"acct/a", b"100")
    cli.put(b"acct/b", b"50")

    def transfer(txn):
        a = int(txn.get(b"acct/a"))
        b = int(txn.get(b"acct/b"))
        txn.put(b"acct/a", str(a - 10).encode())
        txn.put(b"acct/b", str(b + 10).encode())

    STM(cli).run(transfer)
    assert cli.get(b"acct/a").value == b"90"
    assert cli.get(b"acct/b").value == b"60"


def test_stm_conflict_retry(cli):
    cli.put(b"ctr", b"0")
    sneaky = {"done": False}

    def bump(txn):
        v = int(txn.get(b"ctr"))
        if not sneaky["done"]:
            # interleave a conflicting write after the read
            cli.put(b"ctr", b"41")
            sneaky["done"] = True
        txn.put(b"ctr", str(v + 1).encode())

    STM(cli).run(bump)
    assert cli.get(b"ctr").value == b"42"  # retried over the new base
