"""Flow-control, snapshot-path, and ring-exhaustion parity tests.

Transliterations of raft/raft_flow_control_test.go (inflights pausing /
freeing: TestMsgAppFlowControlFull / MoveForward / RecvHeartbeat) and
raft/raft_snap_test.go (TestSendingSnapshotSetPendingSnapshot /
TestPendingSnapshotPauseReplication / TestSnapshotFailure /
TestSnapshotSucceed / TestSnapshotAbort), plus the ring-capacity case the
reference cannot hit (its log is unbounded): a follower that lags past
the leader's ring window recovers via MsgSnap.

Driven through RawNode so messages inject exactly like the reference's
r.Step(pb.Message{...}) whitebox calls.
"""
import pytest

from etcd_tpu.models.rawnode import HostMsg, RawNode
from etcd_tpu.storage.raftstorage import (
    ConfState,
    MemoryStorage,
    Snapshot,
    SnapshotMeta,
)
from etcd_tpu.types import (
    MSG_APP,
    MSG_APP_RESP,
    MSG_HEARTBEAT_RESP,
    MSG_SNAP,
    MSG_SNAP_STATUS,
    MSG_UNREACHABLE,
    PR_PROBE,
    PR_REPLICATE,
    PR_SNAPSHOT,
    ROLE_LEADER,
    Spec,
)
from etcd_tpu.utils.config import RaftConfig

# small inflight window so pausing is reachable in a few proposals
SPEC = Spec(M=3, L=16, E=2, K=4, W=2, R=2, A=4)
CFG = RaftConfig(election_tick=3, heartbeat_tick=1, max_inflight=2)


def new_leader():
    """A 3-node group's leader lane with follower 1 in Replicate state
    (the newTestRaft + becomeLeader + BecomeReplicate fixture)."""
    s = MemoryStorage()
    s.apply_snapshot(
        Snapshot(
            meta=SnapshotMeta(
                index=2, term=1, conf_state=ConfState(voters=(0, 1, 2))
            )
        )
    )
    rn = RawNode(CFG, SPEC, s, 0, applied=2)
    rn.campaign()
    term = int(rn.n.term)
    for p in (1, 2):
        rn.step(HostMsg(type=4, to=0, frm=p, term=term))  # MsgVoteResp
    assert int(rn.n.role) == ROLE_LEADER
    rd = rn.ready()
    s.append(rd.entries)
    if rd.hard_state:
        s.set_hard_state(rd.hard_state)
    rn.advance(rd)
    # follower 1 acks the empty entry -> Replicate
    ack(rn, 1, int(rn.n.last_index))
    drain(rn)
    return rn, s


def ack(rn, frm, index, reject=False, hint=0, hint_term=0):
    rn.step(
        HostMsg(
            type=MSG_APP_RESP, to=0, frm=frm, term=int(rn.n.term),
            index=index, reject=reject, reject_hint=hint, log_term=hint_term,
        )
    )


def drain(rn):
    """Harvest pending messages through a Ready/Advance cycle."""
    rd = rn.ready()
    rn.storage.append(rd.entries)
    if rd.hard_state:
        rn.storage.set_hard_state(rd.hard_state)
    rn.advance(rd)
    return rd.messages


def apps_to(msgs, to):
    return [m for m in msgs if m.type == MSG_APP and m.to == to]


def pr(rn, i):
    return rn.status().progress[i]


# -- TestMsgAppFlowControlFull ----------------------------------------------
def test_flow_control_full():
    rn, _ = new_leader()
    # fill follower 1's inflight window
    for k in range(CFG.max_inflight):
        assert rn.propose(100 + k)
        assert len(apps_to(drain(rn), 1)) == 1
    assert pr(rn, 1).inflight_full
    # further proposals are accepted but not sent to the paused follower
    for k in range(3):
        assert rn.propose(200 + k)
        assert apps_to(drain(rn), 1) == []


# -- TestMsgAppFlowControlMoveForward ---------------------------------------
def test_flow_control_move_forward():
    rn, _ = new_leader()
    first = int(rn.n.last_index) + 1
    for k in range(CFG.max_inflight + 2):
        rn.propose(300 + k)
        drain(rn)
    assert pr(rn, 1).inflight_full
    # ack the first in-flight append: window slides, backlog resumes
    ack(rn, 1, first)
    sent = apps_to(drain(rn), 1)
    assert len(sent) == 1 and sent[0].entries
    assert pr(rn, 1).inflight_full  # refilled by the resumed send
    # acking an index below match frees nothing and sends nothing
    ack(rn, 1, first)
    assert apps_to(drain(rn), 1) == []


# -- TestMsgAppFlowControlRecvHeartbeat -------------------------------------
def test_flow_control_heartbeat_resp_frees_one():
    rn, _ = new_leader()
    for k in range(CFG.max_inflight + 2):
        rn.propose(400 + k)
        drain(rn)
    assert pr(rn, 1).inflight_full
    for _ in range(2):
        rn.step(
            HostMsg(type=MSG_HEARTBEAT_RESP, to=0, frm=1, term=int(rn.n.term))
        )
        # one slot freed -> exactly one more append goes out
        assert len(apps_to(drain(rn), 1)) == 1


# -- raft_snap_test.go fixtures ---------------------------------------------
def snapshot_leader():
    """Leader whose ring has compacted past follower 2's position, with a
    MsgSnap already sent (TestSendingSnapshotSetPendingSnapshot)."""
    rn, s = new_leader()
    # commit+apply a batch with follower 1 only; follower 2 stays at 0
    for k in range(4):
        rn.propose(500 + k)
        drain(rn)
        ack(rn, 1, int(rn.n.last_index))
        drain(rn)
    assert int(rn.n.applied) == int(rn.n.last_index)
    rn.compact_to(int(rn.n.applied))
    # follower 2 rejects the pending probe (prev = its next-1 = 2) with a
    # hint of 0: the decremented next falls below the compaction point
    probe_prev = pr(rn, 2).next - 1
    ack(rn, 2, probe_prev, reject=True, hint=0, hint_term=0)
    msgs = drain(rn)
    snaps = [m for m in msgs if m.type == MSG_SNAP and m.to == 2]
    assert len(snaps) == 1
    return rn, s, snaps[0]


def test_sending_snapshot_sets_pending():
    rn, _, snap = snapshot_leader()
    p = pr(rn, 2)
    assert p.state == PR_SNAPSHOT
    assert p.pending_snapshot == int(rn.n.applied)
    assert snap.snapshot.meta.index == int(rn.n.applied)


# -- TestPendingSnapshotPauseReplication ------------------------------------
def test_pending_snapshot_pauses_replication():
    rn, _, _ = snapshot_leader()
    rn.propose(600)
    assert apps_to(drain(rn), 2) == []


# -- TestSnapshotFailure -----------------------------------------------------
def test_snapshot_failure():
    rn, _, _ = snapshot_leader()
    rn.step(
        HostMsg(type=MSG_SNAP_STATUS, to=0, frm=2, term=int(rn.n.term),
                reject=True)
    )
    p = pr(rn, 2)
    assert p.state == PR_PROBE
    assert p.pending_snapshot == 0
    assert p.next == 1  # match(0) + 1
    assert p.paused  # probe_sent until the next heartbeat resp


# -- TestSnapshotSucceed -----------------------------------------------------
def test_snapshot_succeed():
    rn, _, _ = snapshot_leader()
    rn.step(
        HostMsg(type=MSG_SNAP_STATUS, to=0, frm=2, term=int(rn.n.term),
                reject=False)
    )
    p = pr(rn, 2)
    assert p.state == PR_PROBE
    assert p.pending_snapshot == 0
    assert p.next == int(rn.n.applied) + 1
    assert p.paused


# -- TestSnapshotAbort (via AppResp >= pending) ------------------------------
def test_snapshot_abort_on_app_resp():
    rn, _, snap = snapshot_leader()
    # the follower applied the snapshot out of band and acks at its index
    ack(rn, 2, snap.snapshot.meta.index)
    p = pr(rn, 2)
    assert p.state == PR_REPLICATE
    assert p.pending_snapshot == 0
    assert p.match == snap.snapshot.meta.index


# -- MsgUnreachable ----------------------------------------------------------
def test_unreachable_drops_to_probe():
    rn, _ = new_leader()
    assert pr(rn, 1).state == PR_REPLICATE
    rn.step(
        HostMsg(type=MSG_UNREACHABLE, to=0, frm=1, term=int(rn.n.term))
    )
    p = pr(rn, 1)
    assert p.state == PR_PROBE
    assert p.next == p.match + 1


# -- ring exhaustion + recovery via MsgSnap ---------------------------------
def test_ring_exhaustion_recovers_via_snapshot():
    """A follower that lags past the leader's ring window: the leader's
    ring auto-compacts at the applied cursor (apply_round, the
    triggerSnapshot analog), replication to the laggard falls back to
    MsgSnap, and the restored follower catches up to matching state."""
    rn, s = new_leader()
    f2s = MemoryStorage()
    f2s.apply_snapshot(
        Snapshot(
            meta=SnapshotMeta(
                index=2, term=1, conf_state=ConfState(voters=(0, 1, 2))
            )
        )
    )
    f2 = RawNode(CFG, SPEC, f2s, 2, applied=2)

    # push well past ring capacity (L=16) with only follower 1 acking
    for k in range(SPEC.L + 8):
        rn.propose(700 + k)
        drain(rn)
        ack(rn, 1, int(rn.n.last_index))
        drain(rn)
    assert int(rn.n.snap_index) > 2, "leader ring never compacted"

    # heal: follower 2 reports in; the leader must fall back to MsgSnap
    rn.step(HostMsg(type=MSG_HEARTBEAT_RESP, to=0, frm=2, term=int(rn.n.term)))
    msgs = drain(rn)
    snaps = [m for m in msgs if m.type == MSG_SNAP and m.to == 2]
    assert len(snaps) == 1, f"expected MsgSnap, got {msgs}"

    # deliver the snapshot, then run the ack/append loop to convergence
    f2.step(snaps[0])
    for _ in range(8):
        rd = f2.ready()
        f2s.set_hard_state(rd.hard_state) if rd.hard_state else None
        f2s.append(rd.entries)
        if rd.snapshot:
            f2s.apply_snapshot(rd.snapshot)
        f2.advance(rd)
        for m in rd.messages:
            if m.to == 0:
                rn.step(m)
        back = [m for m in drain(rn) if m.to == 2]
        if not back:
            break
        for m in back:
            f2.step(m)

    assert int(f2.n.last_index) == int(rn.n.last_index)
    assert int(f2.n.applied) == int(rn.n.applied)
    assert int(f2.n.applied_hash) == int(rn.n.applied_hash)
    assert pr(rn, 2).state == PR_REPLICATE
