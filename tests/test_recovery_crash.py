"""Crash–restart chaos tier (ISSUE 3): the SIGKILL/restart fault class of
the reference's functional tester (tester/case_sigterm.go + snapshot
cases) run on-device, with the fsync-lag durability model and the
recovery-invariant checkers (leader completeness, log matching across
restart, HardState term monotonicity).

The default tests run a tiny fleet on CPU (<=64 groups, <=2 fault
epochs — the run_smoke.sh configuration); the 262k bench-geometry run
rides behind the `slow` marker and chaos_run.py (CHAOS_CRASH=0.01).
"""
import jax.numpy as jnp
import numpy as np
import pytest

from etcd_tpu.harness.chaos import (
    VIOLATION_KEYS,
    run_chaos,
    summarize_chaos,
)
from etcd_tpu.models.engine import (
    crash_restart_fleet,
    init_fleet,
    wipe_crashed_traffic,
    empty_inbox,
)
from etcd_tpu.models.state import (
    CAPPED_FIELDS,
    DURABLE_FIELDS,
    NodeState,
    REPLAY_FIELDS,
    VOLATILE_FIELDS,
)
from etcd_tpu.types import NONE_ID, ROLE_FOLLOWER, ROLE_LEADER, Spec
from etcd_tpu.utils.config import CrashConfig, RaftConfig

SPEC = Spec(M=5, L=32, E=2, K=4, W=2, R=2, A=4)
CFG = RaftConfig(pre_vote=True, check_quorum=True)


def assert_safe(rep):
    for k in VIOLATION_KEYS:
        assert rep[k] == 0, rep


def test_chaos_crash_restart_small_fleet():
    """Seeded small-fleet run with crash faults stacked on the network
    mix: all six checkers stay zero, the fleet recovers, and crashes
    actually happened (the fault class is live, not vacuously safe)."""
    rep = run_chaos(
        SPEC, CFG, C=16, rounds=50, epoch_len=25, heal_len=25, seed=1,
        drop_p=0.03, delay_p=0.08, partition_p=0.2,
        crash_p=0.04, crash=CrashConfig(down_rounds=2),
    )
    assert_safe(rep)
    assert rep["crashes_injected"] > 0
    # every injected crash restarts: crashes only inject in fault
    # epochs and the run always ends on a heal epoch whose length (25)
    # exceeds down_rounds (2), so no down-timer survives to the end
    assert rep["restarts_completed"] == rep["crashes_injected"]
    summary = summarize_chaos(rep, rounds=50, epoch_len=25, heal_len=25)
    assert summary["safe"] and summary["recovered"] and summary["lively"], (
        rep, summary)


def test_chaos_crash_persist_nothing_fires_checker():
    """The deliberately-broken durability model (persist nothing past the
    snapshot) must trip the leader-completeness checker: enough crashes
    drop a committed index below quorum holdership. Proves the checker
    is live — a chaos tier whose checkers cannot fire proves nothing.

    Deliberately the SAME cfg/spec/epoch geometry (and delay_p > 0) as
    the honest-model test above: the durability knobs are runtime
    operands, so this run reuses the epoch programs that test already
    traced (harness/chaos.py _epoch_program) instead of paying a second
    ~60s trace in the smoke tier."""
    rep = run_chaos(
        SPEC, CFG, C=16, rounds=25, epoch_len=25, heal_len=25, seed=3,
        drop_p=0.0, delay_p=0.08, partition_p=0.0,
        crash_p=0.12, crash=CrashConfig(down_rounds=2, durability="none"),
    )
    assert rep["lost_commit"] > 0, rep


def test_crash_restart_fleet_field_classification():
    """The wipe implements models/state.py's durability table exactly,
    field by field — and the table covers every NodeState field, so a
    future field cannot silently survive (or lose) a simulated crash."""
    all_fields = set(NodeState.__dataclass_fields__)
    classified = (set(DURABLE_FIELDS) | set(CAPPED_FIELDS)
                  | set(REPLAY_FIELDS) | set(VOLATILE_FIELDS))
    assert classified == all_fields, classified ^ all_fields
    assert len(DURABLE_FIELDS + CAPPED_FIELDS + REPLAY_FIELDS
               + VOLATILE_FIELDS) == len(all_fields)  # no double-class

    spec = SPEC
    C = 4
    state = init_fleet(spec, C, seed=9)
    # dirty every volatile/derived field so "reset" is distinguishable
    ones2 = jnp.ones_like(state.commit)
    state = state.replace(
        term=state.term + 4, vote=jnp.zeros_like(state.vote),
        commit=ones2 * 6, last_index=ones2 * 8, applied=ones2 * 5,
        applied_hash=ones2 * 1234, snap_index=ones2 * 2,
        snap_term=ones2 * 3, snap_hash=ones2 * 77,
        role=jnp.full_like(state.role, ROLE_LEADER),
        lead=jnp.zeros_like(state.lead),
        election_elapsed=ones2 * 3, heartbeat_elapsed=ones2 * 1,
        match=jnp.ones_like(state.match) * 7,
        next_idx=jnp.ones_like(state.next_idx) * 9,
        votes_granted=jnp.ones_like(state.votes_granted),
        uncommitted_size=ones2 * 2,
        ro_count=ones2 * 1,
    )
    crashed = jnp.ones((spec.M, C), jnp.bool_).at[0, 0].set(False)
    stable = ones2 * 7           # one entry (index 8) past the fsync floor
    rand_to = ones2 * 13
    out, lost = crash_restart_fleet(spec, state, crashed, stable, rand_to)

    g = lambda s, name: np.asarray(getattr(s, name))
    # DURABLE: untouched everywhere
    for f in DURABLE_FIELDS:
        np.testing.assert_array_equal(g(out, f), g(state, f), err_msg=f)
    # CAPPED: last_index drops to stable (> snap floor here), commit
    # follows; the uncrashed lane keeps its originals
    assert g(out, "last_index")[0, 0] == 8
    assert g(out, "commit")[0, 0] == 6
    assert (g(out, "last_index")[:, 1:] == 7).all()
    assert (g(out, "commit")[:, 1:] == 6).all()
    # entries_lost: one entry per crashed node
    assert int(lost) == int(np.asarray(crashed).sum())
    # REPLAY: rewound to the snapshot cursor/ConfState
    assert (g(out, "applied")[:, 1:] == 2).all()
    assert (g(out, "applied_hash")[:, 1:] == 77).all()
    np.testing.assert_array_equal(
        g(out, "voters")[:, :, 1:], g(state, "snap_voters")[:, :, 1:])
    # VOLATILE: fresh-follower boot values (randomized_timeout re-drawn
    # from the supplied draw)
    assert (g(out, "role")[:, 1:] == ROLE_FOLLOWER).all()
    assert (g(out, "lead")[:, 1:] == NONE_ID).all()
    assert (g(out, "election_elapsed")[:, 1:] == 0).all()
    assert (g(out, "randomized_timeout")[:, 1:] == 13).all()
    assert (g(out, "match")[:, :, 1:] == 0).all()
    assert (g(out, "next_idx")[:, :, 1:] == 8).all()  # durable_last + 1
    assert (g(out, "votes_granted")[:, :, 1:] == 0).all()
    assert (g(out, "uncommitted_size")[:, 1:] == 0).all()
    assert (g(out, "ro_count")[:, 1:] == 0).all()
    # the uncrashed lane (m=0, c=0) kept ALL its volatile state
    assert g(out, "role")[0, 0] == ROLE_LEADER
    assert g(out, "match")[0, :, 0].max() == 7

    # persist-nothing drops the log to the snapshot outright
    out2, lost2 = crash_restart_fleet(
        spec, state, crashed, stable, rand_to, keep_log=False)
    assert (g(out2, "last_index")[:, 1:] == 2).all()
    assert (g(out2, "commit")[:, 1:] == 2).all()
    assert int(lost2) == 6 * int(np.asarray(crashed).sum())


def test_wipe_crashed_traffic_kills_rows_and_cols():
    spec = SPEC
    C = 3
    inbox = empty_inbox(spec, C)
    t = jnp.ones_like(inbox.type)  # every slot carries a message
    inbox = inbox.replace(type=t)
    crashed = jnp.zeros((spec.M, C), jnp.bool_).at[2, 1].set(True)
    out = wipe_crashed_traffic(spec, inbox, crashed)
    t5 = np.asarray(out.type).reshape(spec.M, spec.K, spec.M, C)
    assert (t5[2, :, :, 1] == 0).all()   # everything FROM node 2, lane 1
    assert (t5[:, :, 2, 1] == 0).all()   # everything TO node 2, lane 1
    # all other traffic survives
    mask = np.ones_like(t5, bool)
    mask[2, :, :, 1] = False
    mask[:, :, 2, 1] = False
    assert (t5[mask] == 1).all()


def test_summarize_chaos_gates():
    base = {
        "groups": 10,
        "multi_leader": 0, "hash_mismatch": 0, "commit_regress": 0,
        "lost_commit": 0, "log_divergence": 0, "term_regress": 0,
        "groups_with_leader_after_heal": 10,
        "heal_commits_last_epoch": 5,
        # two fault epochs + one WaitHealth extension row (must not
        # count toward the fault-epoch liveness floor)
        "epoch_commits": [(120, 300), (80, 250), (0, 40)],
    }
    s = summarize_chaos(base, rounds=150, epoch_len=50, heal_len=25)
    assert s["safe"] and s["recovered"]
    assert s["faulted_commits"] == 200
    assert s["faulted_liveness_floor"] == int(0.2 * 10 * 100)
    assert s["lively"]

    # any recovery-invariant counter breaks "safe"
    s2 = summarize_chaos({**base, "lost_commit": 1},
                         rounds=150, epoch_len=50, heal_len=25)
    assert not s2["safe"]
    # a report from a pre-crash-tier driver (no new keys) still gates
    legacy = {k: v for k, v in base.items()
              if k not in ("lost_commit", "log_divergence", "term_regress")}
    assert summarize_chaos(legacy, rounds=150, epoch_len=50,
                           heal_len=25)["safe"]
    # a wedged fleet fails the liveness floor
    s3 = summarize_chaos({**base, "epoch_commits": [(3, 300), (2, 250)]},
                         rounds=150, epoch_len=50, heal_len=25)
    assert not s3["lively"]
    # missing leader after heal fails recovery
    s4 = summarize_chaos({**base, "groups_with_leader_after_heal": 9},
                         rounds=150, epoch_len=50, heal_len=25)
    assert not s4["recovered"]


def test_crash_chaos_rejects_singleton():
    with pytest.raises(ValueError, match="M >= 2"):
        run_chaos(Spec(M=1, L=8, E=1, K=1, W=2, R=2, A=2), CFG, C=4,
                  rounds=10, crash_p=0.1)


@pytest.mark.slow
def test_chaos_crash_262k_groups():
    """The acceptance-scale run (bench geometry, crash faults stacked on
    the standard network mix) — exercised on TPU via chaos_run.py
    (CHAOS_C=262144 CHAOS_CRASH=0.01); here behind the slow marker."""
    spec = Spec(M=5, L=16, E=1, K=2, W=4, R=2, A=2)
    cfg = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=4,
                     inbox_bound=4, coalesce_commit_refresh=True,
                     wire_int16=True)
    rep = run_chaos(
        spec, cfg, C=262_144, rounds=200, epoch_len=50, heal_len=25,
        seed=0, drop_p=0.02, delay_p=0.05, partition_p=0.1,
        crash_p=0.01, crash=CrashConfig(down_rounds=3),
    )
    assert_safe(rep)
    s = summarize_chaos(rep, rounds=200, epoch_len=50, heal_len=25)
    assert s["recovered"] and s["lively"], (rep, s)
