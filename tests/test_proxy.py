"""grpcproxy-analog tests: range caching with write invalidation, watch
coalescing (one upstream watcher, N subscribers), passthrough
(server/proxy/grpcproxy: cache/store.go, watch_broadcast.go)."""
import base64
import json
import urllib.request

import pytest

from etcd_tpu.embed import Config, start_etcd
from etcd_tpu.proxy import ProxyServer


def b64(s) -> str:
    if isinstance(s, str):
        s = s.encode()
    return base64.b64encode(s).decode()


@pytest.fixture(scope="module")
def stack():
    etcd = start_etcd(Config(cluster_size=3, auto_tick=False))
    proxy = ProxyServer(etcd.client_url).start()
    yield etcd, proxy
    proxy.stop()
    etcd.close()


def call(port, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())


def test_proxy_passthrough_and_cache(stack):
    etcd, proxy = stack
    p = proxy.port
    call(p, "/v3/kv/put", {"key": b64("px/a"), "value": b64("1")})
    q = {"key": b64("px/a"), "serializable": True}
    r1 = call(p, "/v3/kv/range", q)
    r2 = call(p, "/v3/kv/range", q)  # served from cache
    assert r1["kvs"] == r2["kvs"]
    assert proxy.proxy.cache.hits >= 1
    # a write through the proxy invalidates the cached range
    call(p, "/v3/kv/put", {"key": b64("px/a"), "value": b64("2")})
    r3 = call(p, "/v3/kv/range", q)
    assert base64.b64decode(r3["kvs"][0]["value"]) == b"2"


def test_proxy_watch_coalescing(stack):
    etcd, proxy = stack
    p = proxy.port
    create = {"key": b64("px/w"), "range_end": b64("px/w\xff")}
    w1 = call(p, "/v3/watch", {"create_request": dict(create)})["watch_id"]
    w2 = call(p, "/v3/watch", {"create_request": dict(create)})["watch_id"]
    assert w1 != w2
    # both subscribers share ONE upstream watcher
    assert len(proxy.proxy.watches._bcasts) == 1
    call(p, "/v3/kv/put", {"key": b64("px/w1"), "value": b64("x")})
    e1 = call(p, "/v3/watch", {"poll_request": {"watch_id": w1}})["events"]
    e2 = call(p, "/v3/watch", {"poll_request": {"watch_id": w2}})["events"]
    assert len(e1) == 1 and len(e2) == 1  # both saw the broadcast event
    assert call(p, "/v3/watch", {"cancel_request": {"watch_id": w1}})["canceled"]
    assert call(p, "/v3/watch", {"cancel_request": {"watch_id": w2}})["canceled"]
    assert len(proxy.proxy.watches._bcasts) == 0  # upstream dropped


def test_proxy_lease_keepalive_fanin(stack):
    """N clients refreshing one lease through the proxy ride ONE upstream
    keepalive inside the TTL/3 refresh window (grpcproxy/lease.go:34)."""
    etcd, proxy = stack
    p = proxy.port
    call(p, "/v3/lease/grant", {"ID": "9001", "TTL": "60"})
    lc = proxy.proxy.leases
    base_up = lc.upstream_sent
    for _ in range(4):  # 4 rapid keepalives, window = 20s
        r = call(p, "/v3/lease/keepalive", {"ID": "9001"})
        assert int(r["TTL"]) > 0
    assert lc.upstream_sent == base_up + 1
    assert lc.coalesced >= 3
    # revoke drops the cached stream: nothing stale survives
    call(p, "/v3/lease/revoke", {"ID": "9001"})
    assert 9001 not in lc._last


def test_proxy_health_get_passthrough(stack):
    _, proxy = stack
    with urllib.request.urlopen(
        f"http://127.0.0.1:{proxy.port}/health"
    ) as r:
        assert json.loads(r.read())["health"] == "true"
