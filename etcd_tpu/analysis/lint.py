"""Level-1 lint: pluggable AST rules over the repo source.

Stdlib-only (ast + tokenize) so the lint tier costs milliseconds and
never initializes jax. Each rule is a registered checker over one
parsed file; findings carry (rule, path, line, message) and print as
``path:line: [rule] message``.

Suppressions are inline comments the linter itself parses:

  * ``# lint: allow(rule) -- reason``         this line only
  * ``# lint: allow-def(rule) -- reason``     the next ``def`` (whole body)
  * ``# lint: allow-module(rule) -- reason``  the whole file

A suppression without a ``-- reason`` justification is itself a finding
(rule ``suppression``): the point of the mechanism is that every
exemption carries its rationale at the use site.
"""
from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path

# ---------------------------------------------------------------------------
# findings + suppressions
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


_ALLOW_RE = re.compile(
    r"lint:\s*(allow(?:-def|-module)?)\s*\(\s*([\w,\s-]+?)\s*\)"
    r"\s*(?:--\s*(\S.*))?$")


class Suppressions:
    """Per-file suppression table built from comment tokens."""

    def __init__(self, source: str, tree: ast.Module, path: str):
        self.line_allow: dict[int, set[str]] = {}
        self.module_allow: set[str] = set()
        self.findings: list[Finding] = []
        def_spans = [(n.lineno, n.end_lineno or n.lineno)
                     for n in ast.walk(tree)
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))]
        def_spans.sort()
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(source).readline))
        except tokenize.TokenError:
            return
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _ALLOW_RE.search(tok.string)
            if m is None:
                continue
            kind, rules_s, reason = m.groups()
            rules = {r.strip() for r in rules_s.split(",") if r.strip()}
            line = tok.start[0]
            if not reason:
                self.findings.append(Finding(
                    "suppression", path, line,
                    f"{kind}({','.join(sorted(rules))}) has no "
                    "'-- justification'; every exemption must say why"))
                continue
            if kind == "allow-module":
                self.module_allow |= rules
            elif kind == "allow":
                self.line_allow.setdefault(line, set()).update(rules)
            else:  # allow-def: attach to the first def at/after the comment
                span = next(((s, e) for s, e in def_spans if s >= line),
                            None)
                if span is None:
                    self.findings.append(Finding(
                        "suppression", path, line,
                        "allow-def comment has no following def"))
                    continue
                for ln in range(span[0], span[1] + 1):
                    self.line_allow.setdefault(ln, set()).update(rules)

    def allows(self, rule: str, line: int) -> bool:
        return (rule in self.module_allow
                or rule in self.line_allow.get(line, ()))


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------

RULES: dict[str, "object"] = {}


def rule(name: str):
    def deco(fn):
        RULES[name] = fn
        return fn
    return deco


def _is_traced_module(rel: str) -> bool:
    """The modules whose code runs under jit in the round/epoch programs
    (plus their host edges, which must be explicitly suppressed)."""
    return (rel.startswith("etcd_tpu/models/")
            or rel.startswith("etcd_tpu/parallel/")
            or rel == "etcd_tpu/harness/chaos.py")


def _dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# --- rule: env-knob --------------------------------------------------------

# Runtime-platform plumbing, not behavior knobs: reading these raw is the
# documented pattern (bench/chaos_run JAX_PLATFORMS forwarding,
# verify_drive's XLA_FLAGS host-device-count append).
ENV_ALLOWLIST = frozenset({"JAX_PLATFORMS", "XLA_FLAGS"})


@rule("env-knob")
def check_env_knob(rel: str, tree: ast.Module, source: str):
    """Raw os.environ value reads outside utils/knobs.py. Presence
    checks (``"X" in os.environ``) and child-env construction
    (``dict(os.environ, ...)``) stay legal — only value reads must go
    through the env_* helpers so a typo'd knob exits 2 instead of
    silently selecting a default (the PR-10 knob-hygiene contract)."""
    if rel == "etcd_tpu/utils/knobs.py":
        return
    for node in ast.walk(tree):
        key = None
        if (isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)
                and _dotted(node.value) in ("os.environ", "environ")):
            key = node.slice
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr in ("get", "setdefault")
              and _dotted(node.func.value) in ("os.environ", "environ")):
            key = node.args[0] if node.args else None
        elif (isinstance(node, ast.Call)
              and _dotted(node.func) in ("os.getenv", "getenv")):
            key = node.args[0] if node.args else None
        else:
            continue
        if (isinstance(key, ast.Constant) and isinstance(key.value, str)
                and key.value in ENV_ALLOWLIST):
            continue
        name = (key.value if isinstance(key, ast.Constant) else "<dynamic>")
        yield Finding(
            "env-knob", rel, node.lineno,
            f"raw os.environ read of {name!r}; route through "
            "etcd_tpu.utils.knobs (env_int/env_float/env_bool/env_str) "
            "so a bad value exits 2 before device work")


# --- rule: host-sync -------------------------------------------------------

_REDUCTIONS = frozenset({"sum", "max", "min", "mean", "any", "all", "prod",
                         "item"})


@rule("host-sync")
def check_host_sync(rel: str, tree: ast.Module, source: str):
    """Host-sync calls inside the traced-round modules: .item(),
    np.asarray on device values, jax.device_get, and int()/float() over
    an array reduction. Each one is a device->host transfer that blocks
    the round pipeline; legitimate host edges (report paths, host
    adapters) must carry an allow-def/allow-module justification."""
    if not _is_traced_module(rel):
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "item" and not node.args):
            yield Finding("host-sync", rel, node.lineno,
                          ".item() forces a device->host sync")
        elif dotted in ("np.asarray", "numpy.asarray", "np.array",
                        "numpy.array"):
            yield Finding("host-sync", rel, node.lineno,
                          f"{dotted}(...) pulls the operand to host")
        elif dotted in ("jax.device_get", "device_get"):
            yield Finding("host-sync", rel, node.lineno,
                          "jax.device_get is a device->host transfer")
        elif (isinstance(node.func, ast.Name)
              and node.func.id in ("int", "float") and node.args):
            arg = node.args[0]
            if (isinstance(arg, ast.Call)
                    and isinstance(arg.func, ast.Attribute)
                    and arg.func.attr in _REDUCTIONS):
                yield Finding(
                    "host-sync", rel, node.lineno,
                    f"{node.func.id}(...{arg.func.attr}()) materializes a "
                    "device reduction on host")


# --- rule: debug-print -----------------------------------------------------


@rule("debug-print")
def check_debug_print(rel: str, tree: ast.Module, source: str):
    """Leftover jax.debug.print / jax.debug.breakpoint / breakpoint():
    debugging scaffolds that compile a host callback into the round
    program (and tank TPU throughput) or stop a headless run."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        if dotted in ("jax.debug.print", "jax.debug.breakpoint",
                      "debug.print", "debug.breakpoint"):
            yield Finding("debug-print", rel, node.lineno,
                          f"leftover {dotted}(...) compiles a host "
                          "callback into the traced program")
        elif dotted == "breakpoint":
            yield Finding("debug-print", rel, node.lineno,
                          "leftover breakpoint() call")


# --- rule: undefined-name --------------------------------------------------

_BUILTIN_EXTRAS = frozenset({
    "__file__", "__name__", "__doc__", "__builtins__", "__spec__",
    "__package__", "__loader__", "__path__", "__debug__",
    "__annotations__", "__dict__", "__class__",
})


class _Scope:
    def __init__(self, kind: str, parent: "_Scope | None"):
        self.kind = kind  # module | function | class | comprehension
        self.parent = parent
        self.bound: set[str] = set()

    def resolves(self, name: str) -> bool:
        s: _Scope | None = self
        while s is not None:
            # class scopes are invisible to code nested inside them
            # (real Python name resolution skips them for functions)
            if s is self or s.kind != "class":
                if name in s.bound:
                    return True
            s = s.parent
        return False


def _bind_target(scope: _Scope, node: ast.AST) -> None:
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            scope.bound.add(n.id)
        elif isinstance(n, (ast.MatchAs, ast.MatchStar)) and n.name:
            scope.bound.add(n.name)
        elif isinstance(n, ast.MatchMapping) and n.rest:
            scope.bound.add(n.rest)


class _NameChecker(ast.NodeVisitor):
    """Undefined-name analysis (the PR-9 `margs` class: a name that is
    never bound anywhere in scope, typically live only under an
    env-gated branch so no default test trips it). Deliberately
    flow-insensitive — a name bound ANYWHERE in the enclosing scope
    chain resolves — so use-before-def ordering never false-positives;
    only genuinely dangling names fire."""

    def __init__(self, rel: str, builtins_set: frozenset):
        self.rel = rel
        self.builtins = builtins_set
        self.findings: list[Finding] = []
        self.scope = _Scope("module", None)

    # -- scope construction: two-pass per scope (collect bindings, then
    # -- visit loads) so forward references inside a scope resolve.

    def _collect_stmt(self, scope: _Scope, stmt: ast.AST) -> None:
        for n in self._shallow_walk(stmt):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                scope.bound.add(n.name)
            elif isinstance(n, (ast.Import, ast.ImportFrom)):
                for alias in n.names:
                    base = (alias.asname or alias.name).split(".")[0]
                    scope.bound.add(base)
            elif isinstance(n, ast.Assign):
                for t in n.targets:
                    _bind_target(scope, t)
            elif isinstance(n, (ast.AnnAssign, ast.AugAssign)):
                _bind_target(scope, n.target)
            elif isinstance(n, ast.NamedExpr):
                _bind_target(scope, n.target)
            elif isinstance(n, (ast.For, ast.AsyncFor)):
                _bind_target(scope, n.target)
            elif isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    if item.optional_vars is not None:
                        _bind_target(scope, item.optional_vars)
            elif isinstance(n, ast.ExceptHandler) and n.name:
                scope.bound.add(n.name)
            elif isinstance(n, (ast.Global, ast.Nonlocal)):
                scope.bound.update(n.names)
            elif isinstance(n, ast.Delete):
                for t in n.targets:
                    _bind_target(scope, t)
            elif isinstance(n, (ast.MatchAs, ast.MatchStar,
                                ast.MatchMapping)):
                _bind_target(scope, n)

    @staticmethod
    def _shallow_walk(stmt: ast.AST):
        """Walk a statement without descending into nested function /
        class / lambda / comprehension scopes."""
        stack = [stmt]
        while stack:
            n = stack.pop()
            yield n
            if isinstance(
                    n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                        ast.Lambda, ast.ListComp, ast.SetComp, ast.DictComp,
                        ast.GeneratorExp)):
                continue  # yielded for its own binding; don't descend
            stack.extend(ast.iter_child_nodes(n))

    # -- visiting

    def check_module(self, tree: ast.Module) -> None:
        for stmt in tree.body:
            self._collect_stmt(self.scope, stmt)
        self.generic_visit(tree)

    def _enter_function(self, node, args: ast.arguments) -> None:
        scope = _Scope("function", self.scope)
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            scope.bound.add(a.arg)
        body = node.body if isinstance(node.body, list) else [node.body]
        for stmt in body:
            if isinstance(stmt, ast.stmt):
                self._collect_stmt(scope, stmt)
        prev, self.scope = self.scope, scope
        # defaults/decorators/annotations evaluate in the ENCLOSING scope
        # and are visited by the caller's generic traversal; here visit
        # only the body.
        for stmt in body:
            self.visit(stmt)
        self.scope = prev

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        for dec in node.decorator_list:
            self.visit(dec)
        for d in list(node.args.defaults) + [d for d in
                                             node.args.kw_defaults if d]:
            self.visit(d)
        self._enter_function(node, node.args)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        for d in list(node.args.defaults) + [d for d in
                                             node.args.kw_defaults if d]:
            self.visit(d)
        scope = _Scope("function", self.scope)
        for a in (list(node.args.posonlyargs) + list(node.args.args)
                  + list(node.args.kwonlyargs)
                  + ([node.args.vararg] if node.args.vararg else [])
                  + ([node.args.kwarg] if node.args.kwarg else [])):
            scope.bound.add(a.arg)
        prev, self.scope = self.scope, scope
        self.visit(node.body)
        self.scope = prev

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        for dec in node.decorator_list:
            self.visit(dec)
        for base in list(node.bases) + [k.value for k in node.keywords]:
            self.visit(base)
        scope = _Scope("class", self.scope)
        for stmt in node.body:
            self._collect_stmt(scope, stmt)
        prev, self.scope = self.scope, scope
        for stmt in node.body:
            self.visit(stmt)
        self.scope = prev

    def _visit_comp(self, node) -> None:
        scope = _Scope("comprehension", self.scope)
        for gen in node.generators:
            _bind_target(scope, gen.target)
        prev, self.scope = self.scope, scope
        for gen in node.generators:
            self.visit(gen.iter)
            for cond in gen.ifs:
                self.visit(cond)
        if isinstance(node, ast.DictComp):
            self.visit(node.key)
            self.visit(node.value)
        else:
            self.visit(node.elt)
        self.scope = prev

    visit_ListComp = visit_SetComp = visit_GeneratorExp = _visit_comp
    visit_DictComp = _visit_comp

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        # annotations may be strings / forward refs under
        # `from __future__ import annotations`; skip them
        if node.value is not None:
            self.visit(node.value)
        self.visit(node.target)

    def visit_Name(self, node: ast.Name) -> None:
        if not isinstance(node.ctx, ast.Load):
            return
        name = node.id
        if (self.scope.resolves(name) or name in self.builtins
                or name in _BUILTIN_EXTRAS):
            return
        self.findings.append(Finding(
            "undefined-name", self.rel, node.lineno,
            f"name {name!r} is never bound in any enclosing scope "
            "(NameError at runtime — the env-gated `margs` class)"))


@rule("undefined-name")
def check_undefined_name(rel: str, tree: ast.Module, source: str):
    import builtins as _b
    checker = _NameChecker(rel, frozenset(dir(_b)))
    checker.check_module(tree)
    yield from checker.findings


# --- rule: dead-knob -------------------------------------------------------

_ENV_HELPER_RE = re.compile(r"^_?env_(float|int|bool|str|list)$")
_KNOB_NAME_RE = re.compile(r"^[A-Z][A-Z0-9_]*$")


@rule("dead-knob")
def check_dead_knob(rel: str, tree: ast.Module, source: str):
    """Driver knob hygiene (bench.py / chaos_run.py): a knob declared
    via utils/knobs but whose parsed value is never read is dead weight;
    a knob read but absent from the driver's module docstring is
    invisible to users (the docstring IS the help text)."""
    if rel not in ("bench.py", "chaos_run.py"):
        return
    doc = ast.get_docstring(tree) or ""
    loads: dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            loads[node.id] = loads.get(node.id, 0) + 1
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call):
            continue
        call = node.value
        fn_name = None
        if isinstance(call.func, ast.Name):
            fn_name = call.func.id
        elif isinstance(call.func, ast.Attribute):
            fn_name = call.func.attr
        if fn_name is None or not _ENV_HELPER_RE.match(fn_name):
            continue
        knob = next((a.value for a in call.args
                     if isinstance(a, ast.Constant)
                     and isinstance(a.value, str)
                     and _KNOB_NAME_RE.match(a.value)), None)
        if knob is None:
            continue
        if knob not in doc:
            yield Finding(
                "dead-knob", rel, node.lineno,
                f"knob {knob} is read but not documented in the module "
                "docstring (the driver's help text)")
        if (len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and loads.get(node.targets[0].id, 0) == 0):
            yield Finding(
                "dead-knob", rel, node.lineno,
                f"knob {knob} is parsed into "
                f"{node.targets[0].id!r} but the value is never used")


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

DEFAULT_LINT_TARGETS = (
    "bench.py", "chaos_run.py", "verify_drive.py", "__graft_entry__.py",
    "etcd_tpu",
)


def lint_paths(root: Path, targets=DEFAULT_LINT_TARGETS) -> list[Path]:
    out: list[Path] = []
    for t in targets:
        p = root / t
        if p.is_dir():
            out.extend(sorted(p.rglob("*.py")))
        elif p.exists():
            out.append(p)
    return out


def lint_file(path: Path, root: Path,
              rules=None) -> list[Finding]:
    rel = path.relative_to(root).as_posix()
    source = path.read_text()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding("syntax", rel, e.lineno or 0, f"syntax error: {e.msg}")]
    sup = Suppressions(source, tree, rel)
    findings = list(sup.findings)
    selected = RULES if rules is None else {r: RULES[r] for r in rules}
    for name, checker in selected.items():
        for f in checker(rel, tree, source):
            if not sup.allows(name, f.line):
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def run_lint(root: Path, targets=DEFAULT_LINT_TARGETS,
             rules=None) -> list[Finding]:
    findings: list[Finding] = []
    for path in lint_paths(root, targets):
        findings.extend(lint_file(path, root, rules))
    return findings
