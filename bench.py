"""Headline benchmark: batched consensus throughput (+ served writes).

Measures lockstep consensus rounds/sec over a fleet of C concurrent
5-member Raft groups, with one proposal injected per group per round
(every round is real work: append -> MsgApp fan-out -> quorum commit ->
apply), and reports group-rounds/sec against the north-star target of
1M groups x 10k rounds/sec on one v5e-8 (BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

APPLY_MODE != off switches to the END-TO-END SERVED-WRITES benchmark
(evidence: APPLY_r08.json): every round proposes one canonical KV put
per group, and a write only counts once it is committed, APPLIED to an
MVCC revision store, and surfaced as a WATCH DELTA —

  * device: the device-resident apply plane (etcd_tpu/device_mvcc)
    fused into the round program (models/engine.py build_kv_round);
    the per-round watch-delta event count is the host handoff.
  * host: the same consensus fleet with device-apply off; each round
    the committed words cross to the host and replay through one
    WatchableStore/MVCCStore per group with a full-range watcher —
    the kvserver._pump plane, per group (what "writes/s" costs today).

APPLY_MODE=both runs device then host on identical proposal schedules
and cross-checks the canonical latest-record digests on sample lanes
(the same shared fold the differential fuzz gates on).

Knobs (validated up front; a bad value exits 2 before any device work):
  APPLY_MODE   off|device|host|both   (default off)
  APPLY_C      groups                 (default 8192 CPU / 262144 accel)
  APPLY_ROUNDS timed rounds           (default 32)
  APPLY_KEYS   device key-space size  (default 64, 1..511)

KV op words need the int32 wire, so the apply benchmark forces
wire_int16=False (same rule as the membership chaos tier).

Headline-bench knobs (all validated the same way, exit 2 on bad values):
  BENCH_C / BENCH_ROUNDS / BENCH_REPS / BENCH_L / BENCH_W / BENCH_INBOX
  BENCH_CHUNKS  fleet-chunk count; defaults CHUNK-FREE under the diet
  BENCH_WIRE16  int16 wire (default 1 on accel)
  BENCH_PACKED  packed resident state        (default 1 on accel, 0 CPU)
  BENCH_CWIRE   compacted wire carry  (accel default when BENCH_INBOX>0)
  BENCH_SPARSE  outbox out of the scan carry (accel default; needs
                BENCH_DEFERRED; the diet trio is measured in
                BENCH_r09.json — 2.49x lower bytes/group, chunk-free
                1.14x over the 8-way chunked form at C=131072)
  BENCH_DEFERRED / BENCH_CC  round-4/5 specialization A/B toggles
  TELEM         telemetry plane in the observability pass (default 1):
                the report gains commit-latency p50/p99 (rounds), the
                full latency histograms, and a measured
                telemetry_overhead_pct (telemetered round vs bare round
                at the same shape — PROFILE.md round 7)
  TELEM_BUCKETS power-of-two histogram buckets (default 8, 2..16)
  BENCH_BLACKBOX black-box event ring in the observability pass
                (default 1): a second metered program with the ring
                reduction fused in reports the measured marginal
                ring_overhead_pct next to the telemetry overhead
  BENCH_PROFILE capture a jax profiler trace of the timed loop
                (default 0)
``--preflight`` runs the donation + one-trace auditors
(etcd_tpu/analysis/audit.py) on the exact round program these knobs
select, at a small probe C, and exits 1 on a contract violation before
any device allocation.
The report carries the measured footprint: bytes/group from the actual
leaf dtypes/shapes of the timed carries, the dense-form baseline and
their ratio, plus jax.live_arrays() and peak-RSS readings.

TPU rerun (when the accelerator tunnel returns):
  APPLY_MODE=both APPLY_C=262144 python bench.py > APPLY_TPU_r08.json
  BENCH_C=1048576 BENCH_CHUNKS=1 python bench.py > BENCH_TPU_r09.json
    (the diet's chunk-free 1M-group dispatch; BENCH_PACKED=0 restores
    the 8-way chunked round-5 configuration for the A/B)
"""
from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp

# honor an explicit JAX_PLATFORMS=cpu request even though this environment's
# sitecustomize re-registers the accelerator platform at interpreter start
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

# the fleet round program is compile-heavy (minutes per (Spec, C) shape);
# persist compilations so repeated bench runs start hot
os.makedirs(os.path.join(os.path.dirname(__file__) or ".", ".jax_cache"),
            exist_ok=True)
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(__file__) or ".", ".jax_cache"),
)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)

# The reference's measured headline: "benchmarked 10,000 writes/sec"
# (reference README.md:22; BASELINE.md). One group-round = one replicated
# write for one 5-member group, so vs_baseline > 1 beats the reference.
BASELINE_WRITES_PER_SEC = 10_000


def _apply_knobs() -> dict:
    """Parse + validate the APPLY_* env knobs (exit 2 before any device
    work on a bad value — utils/knobs.py, the chaos_run.py pattern)."""
    from etcd_tpu.utils.knobs import env_int, env_str

    mode = env_str("bench", "APPLY_MODE", "off",
                   ("off", "device", "host", "both"))
    out = {"mode": mode}
    for name, default, lo, hi in (
        ("APPLY_C", None, 1, None),
        ("APPLY_ROUNDS", "32", 1, None),
        ("APPLY_KEYS", "64", 1, 511),  # scheme.MAX_KEYS (9-bit key field)
    ):
        out[name] = env_int("bench", name, default, lo, hi)
    return out


def _apply_bench(knobs: dict, platform: str, on_accel: bool) -> None:
    """The served-writes benchmark (see module docstring)."""
    import numpy as np

    from etcd_tpu.device_mvcc import KVSpec, init_kv, scheme
    from etcd_tpu.device_mvcc.apply import kv_digest
    from etcd_tpu.models.engine import (
        _jitted_kv_round,
        empty_inbox,
        init_fleet,
    )
    from etcd_tpu.server.mvcc import MVCCStore
    from etcd_tpu.server.watch import WatchableStore
    from etcd_tpu.types import Spec
    from etcd_tpu.utils.config import RaftConfig

    from etcd_tpu.utils.knobs import env_int

    C = knobs["APPLY_C"] or (262_144 if on_accel else 8192)
    rounds = knobs["APPLY_ROUNDS"]
    keys = knobs["APPLY_KEYS"]
    kvspec = KVSpec(keys=keys)
    # bench geometry minus the int16 wire (KV words use bits 0-27)
    spec = Spec(M=5, L=16, E=1, K=2, W=4, R=2, A=2)
    chunks = env_int(
        "bench", "BENCH_CHUNKS",
        str(max(1, C // 131072)) if on_accel else "1", lo=1)
    cfg = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=4,
                     inbox_bound=spec.M - 1, coalesce_commit_refresh=True,
                     wire_int16=False, fleet_chunks=chunks)
    M, E = spec.M, spec.E
    rnd = _jitted_kv_round(cfg, spec, kvspec, 0)
    z2 = jnp.zeros((M, C), jnp.int32)
    zp = jnp.zeros((M, E, C), jnp.int32)
    no_hup = jnp.zeros((M, C), jnp.bool_)
    no_tick = jnp.zeros((M, C), jnp.bool_)
    keep = jnp.ones((M, M, C), jnp.bool_)
    # one word per round, every group: rotate keys, vary payloads
    words = [scheme.encode_put(r % keys, (100 + r) & scheme.MAX_VAL,
                               r % (scheme.MAX_LEASE + 1))
             for r in range(rounds)]

    def fresh_fleet():
        state = init_fleet(spec, C, seed=0, election_tick=cfg.election_tick)
        inbox = empty_inbox(spec, C, wire_int16=False)
        kv = init_kv(kvspec, C)
        on = jnp.zeros((C,), jnp.bool_)
        state, inbox, kv, _ = rnd(state, inbox, kv, on, z2, zp, zp, z2,
                                  no_hup.at[0].set(True), no_tick, keep)
        for _ in range(24):
            state, inbox, kv, _ = rnd(state, inbox, kv, on, z2, zp, zp, z2,
                                      no_hup, no_tick, keep)
            if int((state.role == 3).sum()) == C:
                break
        assert int((state.role == 3).sum()) == C, "fleet failed to elect"
        return state, inbox, kv

    def run_mode(device: bool):
        """One timed pass. Returns (elapsed_s, served_events,
        digests_or_None). A write is 'served' once its watch delta is
        visible past the device boundary (device: the per-round delta
        count handoff; host: the per-group watcher buffers)."""
        state, inbox, kv = fresh_fleet()
        do_apply = jnp.full((C,), device, jnp.bool_)
        hosts = None
        if not device:
            hosts = []
            for _ in range(C):
                ws = WatchableStore(MVCCStore())
                w = ws.watch(scheme.key_bytes(0), b"\x00")
                hosts.append((ws, w.id))
            cursors = np.zeros(C, np.int64)
        served = 0
        L = spec.L
        t0 = time.perf_counter()
        for r in range(rounds + 4):  # +4 drain rounds: commit lags 2
            w = words[r] if r < rounds else 0
            pl = z2.at[0].set(1) if r < rounds else z2
            pd = zp.at[0, 0].set(w) if r < rounds else zp
            state, inbox, kv, delta = rnd(
                state, inbox, kv, do_apply, pl, pd, zp, z2, no_hup,
                no_tick, keep,
            )
            if device:
                served += int(delta.mask.sum())  # the per-round handoff
            else:
                applied = np.asarray(state.applied[0])
                ld = np.asarray(state.log_data[0])
                for g in range(C):
                    ws, wid = hosts[g]
                    hi = int(applied[g])
                    for idx in range(int(cursors[g]) + 1, hi + 1):
                        word = int(ld[(idx - 1) % L, g])
                        if word:
                            op = scheme.decode(word)
                            txn = ws.kv.write_txn()
                            txn.put(scheme.key_bytes(op["key"]),
                                    scheme.encode_value(op["val"]),
                                    op["lease"])
                            txn.end()
                            ws.notify(txn.events)
                    cursors[g] = hi
                    # drain per round: "served" = delivered to the
                    # consumer (and the buffer never saturates at
                    # Watcher.MAX_BUFFER on long runs)
                    served += len(ws.take_events(wid))
        jax.block_until_ready(state.commit)
        elapsed = time.perf_counter() - t0
        if device:
            digs = np.asarray(kv_digest(kvspec, kv))
        else:
            digs = np.asarray([
                scheme.store_latest_digest(ws.kv, keys)
                for ws, _wid in hosts[:64]
            ])
        return elapsed, served, digs

    rep = {
        "metric": "served_writes_per_sec",
        "unit": (
            "committed+applied+watch-delta writes/s "
            f"(C={C}, rounds={rounds}, keys={keys}, {platform}; "
            "baseline = reference's 10k writes/s headline)"
        ),
        "C": C, "rounds": rounds, "keys": keys, "platform": platform,
    }
    mode = knobs["mode"]
    want = rounds * C
    if mode in ("device", "both"):
        el, served, ddigs = run_mode(device=True)
        rep["device_writes_per_sec"] = round(want / el, 1)
        rep["device_elapsed_s"] = round(el, 3)
        rep["device_served_events"] = served
        rep["device_served_ok"] = served == want
    if mode in ("host", "both"):
        el, served, hdigs = run_mode(device=False)
        rep["host_writes_per_sec"] = round(want / el, 1)
        rep["host_elapsed_s"] = round(el, 3)
        rep["host_served_events"] = served
        rep["host_served_ok"] = served == want
    if mode == "both":
        n = min(64, C)
        rep["digests_match"] = bool((ddigs[:n] == hdigs[:n]).all())
        rep["digest_lanes_checked"] = n
        rep["device_vs_host_speedup"] = round(
            rep["device_writes_per_sec"] / rep["host_writes_per_sec"], 2
        )
        rep["vs_baseline"] = round(
            rep["device_writes_per_sec"] / BASELINE_WRITES_PER_SEC, 2
        )
    print(json.dumps(rep))
# Driver-set stretch goal: 1M groups x 10k lockstep rounds/s on v5e-8
NORTH_STAR_GROUP_ROUNDS_PER_SEC = 1_000_000 * 10_000


def main() -> None:
    import dataclasses as _dc

    # --preflight is the only accepted argument (everything else is
    # knob-driven); an unknown flag exits 2 like a bad knob would
    preflight = "--preflight" in sys.argv[1:]
    unknown = [a for a in sys.argv[1:] if a != "--preflight"]
    if unknown:
        print(f"bench: unknown argument(s): {' '.join(unknown)} "
              f"(only --preflight; configure via BENCH_* knobs)",
              file=sys.stderr)
        raise SystemExit(2)

    # APPLY_* knob validation FIRST — a bad knob exits 2 before any
    # device work (tested in tests/test_device_mvcc.py)
    apply_knobs = _apply_knobs()

    from etcd_tpu.models.engine import build_round, empty_inbox, init_fleet
    from etcd_tpu.parallel.mesh import build_scan_rounds, make_fleet_mesh, shard_fleet
    from etcd_tpu.types import MSG_APP, MSG_APP_RESP, MSG_PROP, Spec
    from etcd_tpu.utils.config import RaftConfig

    from etcd_tpu.utils.knobs import env_bool, env_int

    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)
    if apply_knobs["mode"] != "off":
        return _apply_bench(apply_knobs, platform, on_accel)
    # Every BENCH_* knob is validated up front — a bad value exits 2 with
    # a pointed message before any device work (utils/knobs.py, the same
    # contract as the APPLY_*/CHAOS_* knobs; subprocess-tested in
    # tests/test_device_mvcc.py).
    #
    # clusters-minor layout: the huge C axis is last, so TPU (8,128) tiling
    # pads only the tiny member axes (<=1.6x) and C can grow toward the 1M
    # north-star without tile-padding blowup.
    # defaults match the measured configuration (SCALE_RESULTS.jsonl) so
    # a cold driver run reuses the persisted compile for the same shapes —
    # the north-star 1M-group fleet, resident on one chip
    C = env_int("bench", "BENCH_C", str(1048576 if on_accel else 512), lo=1)
    inner = env_int("bench", "BENCH_ROUNDS",
                    str(16 if on_accel else 8), lo=1)
    reps = env_int("bench", "BENCH_REPS", str(3 if on_accel else 2), lo=1)
    # telemetry plane in the observability pass (models/telemetry.py):
    # latency histograms + p50/p99 next to throughput, plus the measured
    # overhead probe. Same exit-2 contract as every other knob.
    telem = env_bool("bench", "TELEM", "1")
    telem_buckets = env_int("bench", "TELEM_BUCKETS", "8", 2, 16)
    # black-box event ring in the observability pass
    # (models/blackbox.py): a second metered program with the ring
    # reduction fused in, so the report carries the MEASURED marginal
    # ring cost (ring_overhead_pct) next to the telemetry overhead
    bb_on = env_bool("bench", "BENCH_BLACKBOX", "1")
    profile = env_bool("bench", "BENCH_PROFILE", "0")

    # K=2 message slots: in the no-tick steady state each follower sees one
    # MsgApp per round (appends double as heartbeats, exactly the
    # reference's design point of ~1000 writes between 100ms ticks,
    # server/etcdserver/raft.go:33-38).
    # BENCH_L trims the log ring for the 1M-group configuration: state is
    # ring-dominated (~3KB/cluster at L=32), and the steady state needs
    # only enough ring for the commit->apply pipeline (L > 2E + lag).
    L = env_int("bench", "BENCH_L", "16", lo=2)
    W = env_int("bench", "BENCH_W", "4", lo=1)
    spec = Spec(M=5, L=L, E=1, K=2, W=W, R=2, A=2)
    # inbox_bound=M-1: lossless in the one-proposal-per-round steady state
    # (leader sees M-1 acks, followers 1 append; see RaftConfig.inbox_bound
    # and tests/test_inbox_compaction.py), and cuts the dominant serial
    # message loop from M*K+3 to bound+3 steps per round.
    bound = env_int("bench", "BENCH_INBOX", str(spec.M - 1), lo=0)
    # wire_int16 halves the resident inbox (legal at bench horizons: every
    # wire value stays far below 32768 — see RaftConfig.wire_int16)
    wire16 = env_bool("bench", "BENCH_WIRE16", "1" if on_accel else "0")
    # The fleet memory diet (PROFILE.md round 6) is the default ACCEL
    # configuration: bit/width-packed resident state, the compacted
    # [bound, to, C] wire carry, and the dense outbox out of the scan
    # carry. BENCH_PACKED=0 / BENCH_CWIRE=0 / BENCH_SPARSE=0 revert each
    # piece for A/B runs (bit-identity proven in tests/test_packed_state
    # .py and tests/test_sparse_outbox.py). On CPU the default stays
    # dense: the diet trades elementwise shift/mask compute for resident
    # bytes, which pays on an HBM-bandwidth-bound accelerator and
    # measurably does NOT on the compute-bound host backend (~0.7x at
    # C=8192 — BENCH_r09.json carries both readings); opt in explicitly
    # to measure the footprint side on CPU.
    from etcd_tpu.utils.knobs import knob_error

    diet_default = "1" if on_accel else "0"
    packed = env_bool("bench", "BENCH_PACKED", diet_default)
    cwire = env_bool("bench", "BENCH_CWIRE",
                     diet_default if bound > 0 else "0")
    sparse = env_bool("bench", "BENCH_SPARSE", diet_default)
    # an EXPLICIT diet knob that cannot take effect exits 2 like any
    # other bad knob — silently measuring the dense form while the
    # operator believes the diet was on would poison every A/B reading
    if cwire and bound <= 0:
        knob_error("bench", "BENCH_CWIRE=1 needs BENCH_INBOX > 0 "
                   "(the compact carry stores the first `bound` slots)")
    # fleet chunking caps peak HLO-temp HBM (RaftConfig.fleet_chunks).
    # With the diet on, the default is CHUNK-FREE: the packed fleet +
    # donated carry + sparse outbox fit the shapes that used to need the
    # 8-way loop (the pre-diet default kept each resident chunk at
    # <= 131,072 clusters). BENCH_PACKED=0 restores the chunked default
    # for A/B against the round-5 configuration.
    chunks = env_int(
        "bench", "BENCH_CHUNKS",
        "1" if (packed or not on_accel) else str(max(1, C // 131072)),
        lo=1)
    cfg = RaftConfig(pre_vote=True, check_quorum=True,
                     max_inflight=min(4, W),
                     inbox_bound=bound, coalesce_commit_refresh=True,
                     fleet_chunks=chunks, wire_int16=wire16,
                     compact_wire=cwire and bound > 0)
    M, E = spec.M, spec.E

    # trace-time specialization of the timed loop: the steady state has no
    # ticks, no hups (leaders elected below; no ticks -> no timeout fires)
    # and no read-index traffic, so those full-step passes are statically
    # dead — and its WIRE TRAFFIC is exactly {MsgApp, MsgAppResp} plus the
    # local MsgProp, so the other ~14 handler classes are dropped from the
    # compiled step too (RaftConfig.local_steps / message_classes;
    # bit-exact equivalence on live steady traffic proven by
    # tests/test_local_steps.py). Election/settle and the metered
    # observability pass keep the full program.
    deferred = env_bool("bench", "BENCH_DEFERRED", "1")
    if sparse and not deferred and "BENCH_SPARSE" in os.environ:
        # explicitly requested but structurally impossible (the sparse
        # scan carry IS a deferred-emission form) — exit 2, don't
        # silently measure the dense-carry program
        knob_error("bench", "BENCH_SPARSE=1 needs BENCH_DEFERRED=1 "
                   "(the sparse scan carry is a deferred-emission form)")
    steady_cfg = _dc.replace(
        cfg,
        local_steps=("prop",),
        message_classes=(MSG_APP, MSG_APP_RESP, MSG_PROP),
        # emission restructure (PROFILE.md round 4): scan-body handlers
        # record PendingWire intents; one post-scan merge materializes
        # them. Bit-exact on steady traffic (tests/test_deferred_emit.py).
        # BENCH_DEFERRED=0 reverts to immediate emission for A/B runs.
        deferred_emit=deferred,
        # ...and its completion (round 6): the dense outbox leaves the
        # scan carry entirely (tests/test_sparse_outbox.py)
        sparse_outbox=sparse and deferred,
        # the resident fleet state between timed rounds is the packed
        # storage form; pack/unpack bracket the timed scan below
        packed_state=packed,
        # apply-scan specialization (PROFILE.md round 5): the steady
        # program commits only normal entries, so the conf-change apply
        # block (replayed on all Spec.A serial apply slots) drops at
        # trace time (tests/test_apply_specialization.py).
        # BENCH_CC=1 keeps it for A/B runs.
        entry_classes=None if env_bool("bench", "BENCH_CC", "0")
        else ("normal",),
    )

    if preflight:
        # audit the EXACT program shapes this run will execute — the
        # steady-state scan and (when observability is on) the metered
        # round with the driver's donation set — at a small probe C,
        # before the fleet is allocated at BENCH_C
        from etcd_tpu.analysis.audit import run_preflight
        from etcd_tpu.analysis.programs import bench_programs

        finds = []
        for inst in bench_programs(cfg, steady_cfg, spec, telem, bb_on,
                                   buckets=telem_buckets):
            finds += run_preflight(
                inst, progress=lambda m: print(f"# {m}", file=sys.stderr))
        if finds:
            for f in finds:
                print(f, file=sys.stderr)
            print(f"# preflight: {len(finds)} contract violation(s)",
                  file=sys.stderr)
            raise SystemExit(1)
        print("# preflight ok", file=sys.stderr)

    devs = jax.devices()
    mesh = make_fleet_mesh(len(devs)) if len(devs) > 1 else None

    # device (clusters-minor) layout: [M, C] scalars, [M, E, C] proposals,
    # [M(from), M(to), C] keep-mask
    state = init_fleet(spec, C, seed=0, election_tick=cfg.election_tick)
    inbox = empty_inbox(spec, C, wire_int16=cfg.wire_int16,
                        compact_bound=bound if cfg.compact_wire else 0)
    keep = jnp.ones((M, M, C), jnp.bool_)
    z2 = jnp.zeros((M, C), jnp.int32)
    zp = jnp.zeros((M, E, C), jnp.int32)
    no_hup = jnp.zeros((M, C), jnp.bool_)
    tick = jnp.ones((M, C), jnp.bool_)
    no_tick = jnp.zeros((M, C), jnp.bool_)
    if mesh is not None:
        state, inbox, keep = shard_fleet(mesh, state, inbox, keep)

    # -- elect leaders: campaign node 0 everywhere, settle the cascade ------
    step = (
        # donate the fleet buffers: at C=1M state+inbox are ~6GB and the
        # settle phase would otherwise double-buffer them
        jax.jit(build_round(cfg, spec), donate_argnums=(0, 1))
        if mesh is None
        else build_scan_rounds(cfg, spec, mesh, rounds=1)
    )
    hup0 = no_hup.at[0].set(True)
    state, inbox = step(state, inbox, z2, zp, zp, z2, hup0, no_tick, keep)
    for _ in range(24):  # settle to all-leaders AND a quiescent network —
        # timing must start from the steady state, not mid-cascade
        state, inbox = step(state, inbox, z2, zp, zp, z2, no_hup, no_tick, keep)
        if int((state.role == 3).sum()) == C and int((inbox.type != 0).sum()) == 0:
            break
    n_leaders = int((state.role == 3).sum())
    assert n_leaders == C, f"expected {C} leaders, got {n_leaders}"
    assert int((inbox.type != 0).sum()) == 0, "network not quiescent after settle"

    # -- steady state: 1 proposal/group/round at the leader (node 0).
    # No ticks in the timed region: a consensus round is ~ms while the
    # reference's tick is 100ms, so ticking every round would model a
    # wildly faster clock, and each heartbeat fan-out would double the
    # message load. Appends act as leader liveness, as in the reference.
    prop_len = z2.at[0].set(1)
    prop_data = zp.at[0, 0].set(7)
    run = build_scan_rounds(steady_cfg, spec, mesh, rounds=inner)
    args = (prop_len, prop_data, zp, z2, no_hup, no_tick, keep)

    # diet boundary: the settle phase ran the full program on the dense
    # fleet; the timed scan carries the PACKED form (state shrinks ~2.4x,
    # and with fleet_chunks the unpacked temps are chunk-local)
    from etcd_tpu.models.state import pack_fleet, unpack_fleet, unpack_field

    def fleet_commit(st):
        # single-field probe: a full unpack between timed reps would
        # materialize the whole dense fleet just to read one [M, C] row
        return unpack_field(spec, st, "commit") if packed else st.commit

    if packed:
        state = pack_fleet(spec, state)
        if mesh is not None:
            state = shard_fleet(mesh, state)

    state, inbox = run(state, inbox, *args)  # compile + warm
    jax.block_until_ready(jax.tree.leaves(state)[0])
    commit0 = int(fleet_commit(state).min())

    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        state, inbox = run(state, inbox, *args)
        jax.block_until_ready(jax.tree.leaves(state)[0])
        best = min(best, time.perf_counter() - t0)

    # live-bytes accounting AFTER the timed reps: what is actually
    # resident on device, next to the per-leaf-spec numbers reported
    # below (donated carries mean no second fleet copy survives here)
    live_bytes = sum(int(a.nbytes) for a in jax.live_arrays())

    # optional profiler capture of one timed run (the JAX-trace analog of
    # the reference's pprof/tracing endpoints, SURVEY §5)
    if profile:
        trace_dir = os.path.join(
            os.path.dirname(__file__) or ".", "bench_trace"
        )
        with jax.profiler.trace(trace_dir):
            state, inbox = run(state, inbox, *args)
            jax.block_until_ready(jax.tree.leaves(state)[0])
        print(f"# profiler trace written to {trace_dir}", file=sys.stderr)

    rounds_per_sec = inner / best
    group_rounds_per_sec = C * rounds_per_sec

    # sanity: steady-state consensus = ~1 commit/group/round across the
    # whole timed run (commit trails the proposal by the 2-round
    # append->ack pipeline, hence the small slack)
    total_rounds = inner * reps
    min_commit = int(fleet_commit(state).min())
    assert min_commit - commit0 >= total_rounds - 4, (
        f"commit advanced {min_commit - commit0} in {total_rounds} rounds; "
        "fleet is not in one-commit-per-round steady state"
    )
    if packed:
        # back to the dense form for the metered observability pass
        state = unpack_fleet(spec, state)
        if mesh is not None:
            state = shard_fleet(mesh, state)

    # observability pass: a few metered rounds (fused counters +, with
    # TELEM=1, the telemetry plane's latency histograms; see
    # etcd_tpu/models/metrics.py and etcd_tpu/models/telemetry.py) so the
    # report carries election/lag stats and commit-latency percentiles
    from etcd_tpu.models.metrics import (
        build_metered_round,
        metrics_report,
        zero_metrics,
    )
    from etcd_tpu.models.telemetry import init_telemetry, telemetry_report

    # donate the fleet carry AND, when the plane is on, the telemetry
    # carry (positional arg 10): its birth ring / per-node lanes are
    # fleet-scaled and exclusively threaded, so leaving it undonated
    # double-buffers the plane at fleet C (the donation auditor's
    # completeness rule — etcd_tpu/analysis/audit.py — flags exactly
    # this). Never donate the slot while it rides as None.
    met_step = jax.jit(build_metered_round(cfg, spec, with_telemetry=telem),
                       donate_argnums=(0, 1, 10) if telem else (0, 1))
    metrics = zero_metrics()
    tele = init_telemetry(spec, state, buckets=telem_buckets) if telem \
        else None
    mrounds = 8
    # each probe is timed as best-of-`probe_passes` passes of `mrounds`
    # rounds — the same min-of-reps idiom as the main timed loop. A
    # single pass is ~0.2 s at C=512 on one CPU core, where one
    # scheduler hiccup swings the (t_bb - t_obs) / t_bare ratio by tens
    # of points; min over passes makes the overhead figures reproducible
    probe_passes = 3
    # `args` is the timed loop's operand tuple — reusing it keeps the
    # overhead probe's bare-round inputs identical to the metered ones

    def _timed_passes(fn, ready):
        ts = []
        for _ in range(probe_passes):
            t0 = time.perf_counter()
            for _ in range(mrounds):
                fn()
            ready()
            ts.append(time.perf_counter() - t0)
        return ts

    def met_round():
        nonlocal state, inbox, metrics, tele
        if telem:
            state, inbox, metrics, tele = met_step(
                state, inbox, *args, metrics, tele)
        else:
            state, inbox, metrics = met_step(state, inbox, *args, metrics)

    met_round()  # compile + warm
    jax.block_until_ready(metrics.commits)
    # re-zero so the counters cover exactly the timed window (the warm
    # round would otherwise inflate the derived rates); the telemetry
    # carry stays cumulative — its report derives no rates
    metrics = zero_metrics()
    obs_ts = _timed_passes(met_round,
                           lambda: jax.block_until_ready(metrics.commits))
    t_obs = min(obs_ts)
    # the counters span ALL passes, so the report's rate denominator must
    # too; the overhead ratios below use the min-of-passes times instead
    rep = metrics_report(metrics, sum(obs_ts), C, spec.M)
    telemetry_extra = {}
    t_bare = None
    if telem or bb_on:
        # overhead baseline: the same mrounds through the BARE round
        # program (already compiled by the settle phase)
        state, inbox = step(state, inbox, *args)   # warm/settle dispatch
        jax.block_until_ready(jax.tree.leaves(state)[0])

        def bare_round():
            nonlocal state, inbox
            state, inbox = step(state, inbox, *args)

        t_bare = min(_timed_passes(
            bare_round,
            lambda: jax.block_until_ready(jax.tree.leaves(state)[0])))
    if telem:
        trep = telemetry_report(tele)
        # telemetry overhead probe: the delta over the bare program
        # covers the WHOLE observability pass (FleetMetrics counters +
        # telemetry), so it is an UPPER BOUND on the telemetry
        # reductions' own cost — conservative against the <= 10%
        # acceptance bar without compiling a third (metrics-only)
        # program into every bench run
        telemetry_extra = {
            "commit_latency_p50_rounds":
                trep["commit_latency_rounds"]["p50"],
            "commit_latency_p99_rounds":
                trep["commit_latency_rounds"]["p99"],
            "telemetry_overhead_pct": round(
                (t_obs - t_bare) / t_bare * 100, 1),
            "telemetry": trep,
        }
    if bb_on:
        # ring overhead probe: a second metered program with the
        # EventRing reduction added on top of whatever the metered pass
        # above ran; (t_bb - t_obs) isolates the ring's MARGINAL cost,
        # normalized by the bare round like the telemetry probe
        from etcd_tpu.models.blackbox import init_blackbox

        # same donation rule as met_step: the EventRing carry (arg 11,
        # [W, M, C]) is fleet-scaled and exclusively threaded; without
        # telemetry the tele slot (10) is filled POSITIONALLY with None
        # below and stays undonated
        bb_step = jax.jit(
            build_metered_round(cfg, spec, with_telemetry=telem,
                                with_blackbox=True),
            donate_argnums=(0, 1, 10, 11) if telem else (0, 1, 11))
        bb = init_blackbox(spec, state)
        bmetrics = zero_metrics()

        def bb_round():
            nonlocal state, inbox, bmetrics, tele, bb
            if telem:
                state, inbox, bmetrics, tele, bb = bb_step(
                    state, inbox, *args, bmetrics, tele, bb)
            else:
                # tele rides positionally as None so the ring lands at
                # the donated arg 11 slot (keyword args cannot donate)
                state, inbox, bmetrics, bb = bb_step(
                    state, inbox, *args, bmetrics, None, bb)

        bb_round()  # compile + warm
        jax.block_until_ready(bmetrics.commits)
        t_bb = min(_timed_passes(
            bb_round, lambda: jax.block_until_ready(bmetrics.commits)))
        telemetry_extra["ring_overhead_pct"] = round(
            (t_bb - t_obs) / t_bare * 100, 1)

    # -- resident-footprint accounting (the fleet memory diet's measured
    # side): bytes/group from the ACTUAL leaf dtypes/shapes of the timed
    # program's carries, the same accounting the regression budget in
    # tests/test_packed_state.py guards, plus the device/live view
    from etcd_tpu.models.engine import inbox_bytes_per_group
    from etcd_tpu.models.state import state_bytes_per_group
    import resource

    st_b = state_bytes_per_group(spec, packed=packed)
    wi_b = inbox_bytes_per_group(
        spec, wire_int16=wire16,
        compact_bound=bound if cfg.compact_wire else 0)
    st_dense = state_bytes_per_group(spec)
    wi_dense = inbox_bytes_per_group(spec, wire_int16=wire16)
    footprint = {
        "bytes_per_group_state": st_b,
        "bytes_per_group_wire": wi_b,
        "bytes_per_group_total": st_b + wi_b,
        "bytes_per_group_dense_total": st_dense + wi_dense,
        "bytes_ratio_vs_dense": round((st_dense + wi_dense)
                                      / (st_b + wi_b), 2),
        "fleet_bytes_resident": (st_b + wi_b) * C,
        "live_bytes_after_timed_reps": live_bytes,
        "rss_peak_mb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1),
        "packed_state": packed,
        "compact_wire": bool(cfg.compact_wire),
        "sparse_outbox": bool(steady_cfg.sparse_outbox),
        "fleet_chunks": chunks,
        "wire_int16": wire16,
    }

    print(
        json.dumps(
            {
                "metric": "consensus_group_rounds_per_sec",
                "value": round(group_rounds_per_sec, 1),
                # CAVEAT carried in the unit on purpose: one group-round
                # commits+applies one replicated write IN-RING on device
                # (fixed-width words, host checkpoint at epoch
                # granularity); the reference's "writes/s" additionally
                # includes host MVCC apply + fsync'd durability per ack.
                # See README "Host-layer denominator" for that number.
                "unit": f"group-rounds/s (device consensus incl. in-ring "
                f"apply; reference writes/s adds host MVCC+fsync — see "
                f"README) (C={C}, {platform} x{len(devs)}, "
                f"{rounds_per_sec:.1f} rounds/s; baseline = reference's "
                f"10k writes/s headline)",
                "vs_baseline": round(
                    group_rounds_per_sec / BASELINE_WRITES_PER_SEC, 2
                ),
                "vs_north_star_1e10": round(
                    group_rounds_per_sec / NORTH_STAR_GROUP_ROUNDS_PER_SEC, 6
                ),
                "elections_won": rep["elections_won"],
                "leader_losses": rep["leader_losses"],
                "commits_per_group_per_round": rep[
                    "commits_per_group_per_round"
                ],
                "commit_apply_lag_hist": rep["commit_apply_lag_hist"],
                "msgs_dropped": rep["msgs_dropped"],
                **telemetry_extra,
                **footprint,
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
