"""Buffer-donation safety for the fleet carry.

The donated round builders (engine._jitted_round(donate=True), the
mesh.py sharded builders, mesh.build_scan_rounds) single-buffer the
fleet: XLA aliases the output state/inbox onto the inputs, so a round
updates GBs of resident fleet in place instead of holding two copies
across the dispatch — the lever that removes the fleet-chunk loop's
reason to exist. The runtime DELETES the donated input buffers, so:

  * reusing a donated fleet reference must fail loudly (a deleted-buffer
    error), never read stale bytes;
  * the non-donated fallback (RaftEngine's default, donate=False
    builders) must keep working for interactive/debug drivers that
    re-inspect pre-round snapshots.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from etcd_tpu.models.engine import (
    RaftEngine,
    _jitted_round,
    empty_inbox,
    init_fleet,
)
from etcd_tpu.types import Spec
from etcd_tpu.utils.config import RaftConfig

SPEC = Spec(M=3, L=8, E=1, K=1, W=2, R=2, A=2)
CFG = RaftConfig(pre_vote=True)
C = 2


def _args():
    M, E = SPEC.M, SPEC.E
    state = init_fleet(SPEC, C, seed=0, election_tick=CFG.election_tick)
    inbox = empty_inbox(SPEC, C)
    z2 = jnp.zeros((M, C), jnp.int32)
    zp = jnp.zeros((M, E, C), jnp.int32)
    no = jnp.zeros((M, C), jnp.bool_)
    keep = jnp.ones((M, M, C), jnp.bool_)
    return state, inbox, (z2, zp, zp, z2, no, no, keep)


def test_donated_round_refuses_reuse_of_the_fleet():
    """The donated program deletes its input fleet; a second dispatch on
    the same reference must surface a deleted-buffer error cleanly."""
    rnd = _jitted_round(CFG, SPEC, donate=True)
    state, inbox, rest = _args()
    s1, i1 = rnd(state, inbox, *rest)
    assert jax.tree.leaves(state)[0].is_deleted()
    with pytest.raises(Exception, match="[Dd]eleted|[Dd]onated"):
        rnd(state, inbox, *rest)
    # the live carry keeps stepping
    s2, i2 = rnd(s1, i1, *rest)
    assert not jax.tree.leaves(s2)[0].is_deleted()


def test_non_donated_fallback_keeps_inputs_alive():
    """Interactive/debug path: the default builder leaves every input
    buffer live, so pre-round snapshots stay inspectable."""
    rnd = _jitted_round(CFG, SPEC, donate=False)
    state, inbox, rest = _args()
    term0 = np.asarray(state.term).copy()
    rnd(state, inbox, *rest)
    # inputs still readable and unchanged, and re-dispatchable
    assert np.array_equal(np.asarray(state.term), term0)
    rnd(state, inbox, *rest)


def test_raft_engine_donate_mode_steps_and_default_is_safe():
    # default: holding a pre-step snapshot across steps is fine
    eng = RaftEngine(SPEC, CFG, C=C)
    snap = eng.state
    eng.step()
    eng.step()
    assert not jax.tree.leaves(snap)[0].is_deleted()
    # donate=True: the engine reassigns its carry each step, so stepping
    # works; the OLD snapshot's buffers are deleted by the first step
    eng = RaftEngine(SPEC, CFG, C=C, donate=True)
    snap = eng.state
    eng.step()
    eng.step()
    assert jax.tree.leaves(snap)[0].is_deleted()


def test_sharded_builders_donate_and_have_fallback():
    from etcd_tpu.parallel.mesh import (
        build_sharded_round,
        make_fleet_mesh,
        shard_fleet,
    )

    mesh = make_fleet_mesh(2)
    Csh = 8
    M, E = SPEC.M, SPEC.E
    state = init_fleet(SPEC, Csh, seed=0, election_tick=CFG.election_tick)
    inbox = empty_inbox(SPEC, Csh)
    z2 = jnp.zeros((M, Csh), jnp.int32)
    zp = jnp.zeros((M, E, Csh), jnp.int32)
    no = jnp.zeros((M, Csh), jnp.bool_)
    keep = jnp.ones((M, M, Csh), jnp.bool_)
    rest = (z2, zp, zp, z2, no, no, keep)

    state_d, inbox_d = shard_fleet(mesh, state, inbox)
    rnd = build_sharded_round(CFG, SPEC, mesh)  # donates by default
    s1, i1 = rnd(state_d, inbox_d, *rest)
    assert jax.tree.leaves(state_d)[0].is_deleted()
    with pytest.raises(Exception, match="[Dd]eleted|[Dd]onated"):
        rnd(state_d, inbox_d, *rest)

    # fallback form: inputs survive
    state_d, inbox_d = shard_fleet(mesh, state, inbox)
    rnd = build_sharded_round(CFG, SPEC, mesh, donate=False)
    rnd(state_d, inbox_d, *rest)
    rnd(state_d, inbox_d, *rest)
    assert not jax.tree.leaves(state_d)[0].is_deleted()
