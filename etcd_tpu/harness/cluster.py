"""rafttest-style host driver over the batched engine.

Plays the role of the reference's synchronous fake network
(``type network`` in raft/raft_test.go:4633-4748: send-to-quiescence,
drop/cut/isolate/recover) and of the rafttest InteractionEnv verbs
(campaign/propose/stabilize, raft/rafttest/interaction_env_handler.go).
All C clusters advance in lockstep; the per-link fault state is the
engine's keep-mask.

Layout note: the fleet is clusters-minor — every state leaf is
``[M, feature..., C]``, inbox leaves ``[from, K, to, (E,) C]``, the
keep-mask ``[from, to, C]``. Host-side accessors below take (m, c) and
index ``leaf[m, ..., c]``.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from etcd_tpu.models.engine import RaftEngine
from etcd_tpu.types import ENTRY_CONF_CHANGE, ENTRY_NORMAL, NONE_ID, ROLE_LEADER, Spec
from etcd_tpu.utils.config import RaftConfig


@functools.lru_cache(maxsize=16)
def _jitted_tele_update(spec: Spec):
    """One jitted telemetry pass per Spec, shared by every Cluster —
    same tracing-cost rationale as engine._jitted_round."""
    from etcd_tpu.models.telemetry import telemetry_update

    return jax.jit(functools.partial(telemetry_update, spec))


@functools.lru_cache(maxsize=16)
def _jitted_bb_update(spec: Spec):
    """One jitted black-box ring pass per Spec (models/blackbox.py)."""
    from etcd_tpu.models.blackbox import blackbox_update

    return jax.jit(functools.partial(blackbox_update, spec))


class Cluster:
    def __init__(
        self,
        n_members: int = 3,
        C: int = 1,
        spec: Spec | None = None,
        cfg: RaftConfig = RaftConfig(),
        voters=None,
        learners=None,
        seed: int = 0,
        telemetry: bool = False,
        blackbox: bool = False,
    ):
        spec = spec or Spec(M=n_members)
        # canonical lane padding: each distinct C value re-traces the whole
        # jitted round (~30s+ of pjit tracing on the test VM), so small
        # MULTI-cluster tests (2..16 lanes) share one 16-lane program per
        # (cfg, spec); the extra lanes stay idle followers (never hupped
        # or ticked) and every accessor below indexes an explicit
        # c < self.C. C=1 stays unpadded: single-cluster fleets are the
        # overwhelmingly common case (every EtcdCluster), their programs
        # already exist for every cfg, and step-loop-heavy server tests
        # execute a 1-lane round measurably faster than a 16-lane one.
        self.C = C
        self._Cp = C if C <= 1 else (16 if C <= 16 else C)
        if voters is not None:
            voters = jnp.asarray(voters, jnp.bool_)
            if voters.ndim == 2 and voters.shape[0] != self._Cp:
                voters = jnp.concatenate(
                    [voters] + [voters[:1]] * (self._Cp - voters.shape[0])
                )
        if learners is not None:
            learners = jnp.asarray(learners, jnp.bool_)
            if learners.ndim == 2 and learners.shape[0] != self._Cp:
                learners = jnp.concatenate(
                    [learners] + [learners[:1]] * (self._Cp - learners.shape[0])
                )
        self.eng = RaftEngine(spec, cfg, self._Cp, voters, learners, seed)
        self.spec, self.cfg = spec, cfg
        # opt-in telemetry plane (models/telemetry.py): per-group lanes +
        # latency histograms updated beside each step — the serving
        # layer's /metrics histogram source. Read-only over state, so a
        # telemetered Cluster steps bit-identically; padding lanes are
        # sliced off at report time (telemetry_report(groups=self.C)).
        self.tele = None
        if telemetry:
            if cfg.packed_state:
                # init/update read NodeState leaves off the live engine
                # state; the packed storage form would die with an
                # opaque AttributeError (same restriction class as
                # engine.build_kv_round's guard)
                raise ValueError(
                    "Cluster telemetry reads the unpacked fleet; "
                    "construct with packed_state=False")
            from etcd_tpu.models.telemetry import init_telemetry

            self.tele = init_telemetry(spec, self.eng.state)
            self._tele_step = _jitted_tele_update(spec)
        # opt-in black-box event ring (models/blackbox.py): one packed
        # per-round event word per member per lane, the device half of
        # to_chrome_trace. Read-only over state, so stepping stays
        # bit-identical; same packed_state restriction as telemetry.
        self.bb = None
        if blackbox:
            if cfg.packed_state:
                raise ValueError(
                    "Cluster blackbox reads the unpacked fleet; "
                    "construct with packed_state=False")
            from etcd_tpu.models.blackbox import init_blackbox

            self.bb = init_blackbox(spec, self.eng.state)
            self._bb_step = _jitted_bb_update(spec)
        self._next_ctx = 1
        self._reset_inputs()

    # -- queued inputs applied on the next round ----------------------------
    def _reset_inputs(self):
        C, M, E = self._Cp, self.spec.M, self.spec.E
        self._hup = np.zeros((M, C), bool)
        self._plen = np.zeros((M, C), np.int32)
        self._pdata = np.zeros((M, E, C), np.int32)
        self._ptype = np.zeros((M, E, C), np.int32)
        self._rictx = np.zeros((M, C), np.int32)

    def campaign(self, m: int, c: int = 0):
        self._hup[m, c] = True

    def propose(self, m: int, data: int, c: int = 0):
        """Queue one normal-entry proposal at node m."""
        i = int(self._plen[m, c])
        if i >= self.spec.E:
            raise ValueError("proposal batch full for this round")
        self._pdata[m, i, c] = data
        self._ptype[m, i, c] = ENTRY_NORMAL
        self._plen[m, c] = i + 1

    def propose_conf_change(self, m: int, data: int, c: int = 0):
        i = int(self._plen[m, c])
        self._pdata[m, i, c] = data
        self._ptype[m, i, c] = ENTRY_CONF_CHANGE
        self._plen[m, c] = i + 1

    def read_index(self, m: int, c: int = 0) -> int:
        ctx = self._next_ctx
        self._next_ctx += 1
        self._rictx[m, c] = ctx
        return ctx

    # -- faults (raft_test.go:4722-4748) ------------------------------------
    def isolate(self, m: int, c: int | None = None):
        km = np.array(self.eng.keep_mask)
        cs = slice(None) if c is None else c
        km[m, :, cs] = False
        km[:, m, cs] = False
        self.eng.keep_mask = jnp.asarray(km)

    def cut(self, a: int, b: int, c: int | None = None):
        km = np.array(self.eng.keep_mask)
        cs = slice(None) if c is None else c
        km[a, b, cs] = False
        km[b, a, cs] = False
        self.eng.keep_mask = jnp.asarray(km)

    def partition(self, groups: list[list[int]], c: int | None = None):
        """Only links within the same group stay up."""
        M = self.spec.M
        km = np.zeros((M, M), bool)
        for g in groups:
            for a in g:
                for b in g:
                    km[a, b] = True
        full = np.array(self.eng.keep_mask)
        cs = slice(None) if c is None else c
        full[:, :, cs] = km[:, :, None] if c is None else km
        self.eng.keep_mask = jnp.asarray(full)

    def recover(self, c: int | None = None):
        km = np.array(self.eng.keep_mask)
        cs = slice(None) if c is None else c
        km[:, :, cs] = True
        self.eng.keep_mask = jnp.asarray(km)

    # -- stepping ------------------------------------------------------------
    def step(self, tick: bool = False):
        # padding lanes never tick: a broadcast scalar would run
        # elections/heartbeats on the idle canonical lanes, generating
        # traffic that _pending() would then count
        do_tick = np.zeros((self.spec.M, self._Cp), bool)
        if tick:
            do_tick[:, : self.C] = True
        need_pre = self.tele is not None or self.bb is not None
        pre = self.eng.state if need_pre else None
        # the pre-step inbox is what this round consumes; the post-step
        # inbox is what it sent — the ring wants both sides
        pre_inbox = self.eng.inbox if self.bb is not None else None
        self.eng.step(
            prop_len=self._plen,
            prop_data=self._pdata,
            prop_type=self._ptype,
            ri_ctx=self._rictx,
            do_hup=self._hup,
            do_tick=do_tick,
        )
        if self.tele is not None:
            self.tele = self._tele_step(self.tele, pre, self.eng.state)
        if self.bb is not None:
            self.bb = self._bb_step(self.bb, pre, self.eng.state,
                                    inbox=pre_inbox,
                                    outbox=self.eng.inbox)
        self._reset_inputs()

    def reset_telemetry(self) -> None:
        """Open a fresh telemetry measurement window. The counters are
        i32 and meant to be reset per window (FleetTelemetry docstring);
        the serving layer calls this when a scrape detects a wrap."""
        if self.tele is not None:
            from etcd_tpu.models.telemetry import init_telemetry

            self.tele = init_telemetry(self.spec, self.eng.state)

    def tick(self, rounds: int = 1):
        for _ in range(rounds):
            self.step(tick=True)

    def _pending(self) -> int:
        """Pending messages over the REAL lanes only — padding-lane
        traffic must not keep stabilize spinning."""
        return int((np.asarray(self.eng.inbox.type)[..., : self.C] != 0).sum())

    def stabilize(self, max_rounds: int = 64, tick: bool = False):
        """Deliver cascades to quiescence (network.send's loop-to-empty,
        raft_test.go:4713-4720)."""
        self.step(tick=tick)
        for _ in range(max_rounds):
            if self._pending() == 0:
                break
            self.step(tick=tick)
        return self

    # -- whitebox drivers (raft_paper_test.go-style direct message/state
    # manipulation; the batched analog of constructing a raft struct and
    # calling r.Step(pb.Message{...}) directly) ------------------------------
    def set_node(self, m: int, c: int = 0, **fields):
        """Overwrite scalar state leaves for one node, e.g.
        set_node(1, term=2, vote=0, role=ROLE_FOLLOWER)."""
        st = self.eng.state
        upd = {}
        for k, v in fields.items():
            leaf = np.array(getattr(st, k))
            leaf[m, ..., c] = v
            upd[k] = jnp.asarray(leaf)
        self.eng.state = st.replace(**upd)

    def get(self, field: str, m: int, c: int = 0):
        v = np.asarray(getattr(self.eng.state, field)[m, ..., c])
        return v.item() if v.ndim == 0 else v

    def leaf(self, field: str, c: int = 0) -> np.ndarray:
        """One cluster's view of a state leaf, members leading: [M, ...]."""
        return np.asarray(getattr(self.eng.state, field)[..., c])

    def _slot(self, to: int, slot: int, ent: bool = False):
        """Index into the flat inbox middle axis (engine.empty_inbox)."""
        base = slot * self.spec.M + to
        if ent:
            return slice(base * self.spec.E, (base + 1) * self.spec.E)
        return base

    def inject(self, to: int, frm: int, c: int = 0, slot: int = 0, **fields):
        """Place a raw message into the pending inbox (delivered next step)."""
        from etcd_tpu.models.engine import _ENT_FIELDS

        ib = self.eng.inbox
        upd = {}
        fields.setdefault("frm", frm)
        for k, v in fields.items():
            leaf = np.array(getattr(ib, k))
            leaf[frm, self._slot(to, slot, k in _ENT_FIELDS), c] = v
            upd[k] = jnp.asarray(leaf)
        self.eng.inbox = ib.replace(**upd)

    def drain(self, c: int = 0):
        """Drop all pending messages (the fake network's 'filter and discard'
        move, raft_test.go:4750-4760)."""
        ib = self.eng.inbox
        t = np.array(ib.type)
        t[..., c] = 0
        self.eng.inbox = ib.replace(type=jnp.asarray(t))

    def pending(self, c: int = 0):
        """[(to, frm, slot, type), ...] of undelivered messages."""
        M = self.spec.M
        t = np.asarray(self.eng.inbox.type[..., c])  # [from, K*to]
        out = []
        for frm, kt in zip(*np.nonzero(t)):
            out.append(
                (int(kt % M), int(frm), int(kt // M), int(t[frm, kt]))
            )
        return out

    def msg_field(self, field: str, to: int, frm: int, slot: int = 0, c: int = 0):
        from etcd_tpu.models.engine import _ENT_FIELDS

        v = np.asarray(
            getattr(self.eng.inbox, field)[
                frm, self._slot(to, slot, field in _ENT_FIELDS), c
            ]
        )
        return v.item() if v.ndim == 0 else v

    # -- inspection ----------------------------------------------------------
    @property
    def s(self):
        """State view restricted to the REAL lanes: whole-leaf reductions
        in tests (min/all over the clusters axis) must not see the idle
        canonical-padding lanes."""
        if self._Cp == self.C:
            return self.eng.state
        import jax

        return jax.tree.map(lambda x: x[..., : self.C], self.eng.state)

    def np_(self, leaf) -> np.ndarray:
        return np.asarray(leaf)

    def roles(self, c: int = 0) -> np.ndarray:
        return self.leaf("role", c)

    def leaders(self, c: int = 0) -> list[int]:
        lead = self.roles(c) == ROLE_LEADER
        return [int(i) for i in np.nonzero(lead)[0]]

    def leader(self, c: int = 0) -> int:
        """The leader at the highest term (an isolated stale leader may
        coexist, which is legal Raft)."""
        ids = self.leaders(c)
        if not ids:
            return NONE_ID
        terms = self.terms(c)
        return int(max(ids, key=lambda i: terms[i]))

    def terms(self, c: int = 0) -> np.ndarray:
        return self.leaf("term", c)

    def commits(self, c: int = 0) -> np.ndarray:
        return self.leaf("commit", c)

    def log_entries(self, m: int, c: int = 0) -> list[tuple[int, int]]:
        """[(term, data), ...] for indexes (snap, last]."""
        s = self.s
        last = int(s.last_index[m, c])
        snap = int(s.snap_index[m, c])
        lt = np.asarray(s.log_term[m, ..., c])
        ld = np.asarray(s.log_data[m, ..., c])
        out = []
        for i in range(snap + 1, last + 1):
            sl = (i - 1) % self.spec.L
            out.append((int(lt[sl]), int(ld[sl])))
        return out
