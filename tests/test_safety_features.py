"""PreVote (thesis §9.6), CheckQuorum, leadership transfer (thesis §3.10)
and linearizable ReadIndex — mirroring raft_test.go's TestPreVote*,
TestLeaderElectionPreVote, TestCheckQuorum*, TestLeaderTransfer*, and
TestReadOnlyForNewLeader/node read-index flows.
"""
import numpy as np

from etcd_tpu.harness.cluster import Cluster
from etcd_tpu.types import NONE_ID, ROLE_FOLLOWER, ROLE_LEADER, ROLE_PRE_CANDIDATE, Spec
from etcd_tpu.utils.config import RaftConfig

PREVOTE = RaftConfig(pre_vote=True)
CHECKQ = RaftConfig(check_quorum=True)


def test_prevote_election():
    """An election under PreVote completes (pre-vote then real vote)."""
    cl = Cluster(n_members=3, cfg=PREVOTE)
    cl.campaign(0)
    cl.stabilize()
    assert cl.leader() == 0
    assert cl.terms().tolist() == [1, 1, 1]


def test_prevote_no_term_inflation():
    """TestPreVoteWithCheckQuorum flavor: an isolated node under PreVote does
    NOT inflate its term while partitioned, so its return is non-disruptive."""
    cl = Cluster(n_members=3, cfg=PREVOTE)
    cl.campaign(0)
    cl.stabilize()
    cl.propose(0, 1)
    cl.stabilize()
    cl.isolate(2)
    # node 2 times out repeatedly but only pre-campaigns: term stays 1
    for _ in range(40):
        cl.step(tick=True)
    assert int(cl.terms()[2]) == 1
    assert cl.roles()[2] in (ROLE_PRE_CANDIDATE, ROLE_FOLLOWER)
    # leader unharmed
    assert cl.leader() == 0 and int(cl.terms()[0]) == 1
    cl.recover()
    cl.stabilize(tick=True)
    # rejoins without deposing the leader
    assert cl.leader() == 0
    assert cl.terms().tolist() == [1, 1, 1]


def test_without_prevote_term_inflates():
    """Contrast case: without PreVote the isolated node's term grows."""
    cl = Cluster(n_members=3)
    cl.campaign(0)
    cl.stabilize()
    cl.isolate(2)
    for _ in range(40):
        cl.step(tick=True)
    assert int(cl.terms()[2]) > 1


def test_check_quorum_leader_steps_down():
    """TestLeaderElectionWithCheckQuorum: a leader that cannot reach a quorum
    steps down after an election timeout (raft.go:997-1018)."""
    cl = Cluster(n_members=3, cfg=CHECKQ)
    cl.campaign(0)
    cl.stabilize()
    assert cl.leader() == 0
    cl.isolate(0)
    for _ in range(2 * CHECKQ.election_tick + 2):
        cl.step(tick=True)
    assert cl.roles()[0] == ROLE_FOLLOWER


def test_check_quorum_lease_protects_leader():
    """TestFreeStuckCandidateWithCheckQuorum flavor: under CheckQuorum,
    followers in contact with a live leader refuse votes (the lease check,
    raft.go:855-862), so a rejoining inflated-term node cannot depose the
    leader by vote; instead it is re-absorbed."""
    cl = Cluster(n_members=3, cfg=CHECKQ)
    cl.campaign(0)
    cl.stabilize()
    cl.propose(0, 3)
    cl.stabilize()
    cl.isolate(2)
    for _ in range(35):
        cl.step(tick=True)
    inflated = int(cl.terms()[2])
    assert inflated > 1
    cl.recover()
    cl.stabilize(tick=True)
    for _ in range(12):
        cl.step(tick=True)
    cl.stabilize(tick=True)
    # one leader again; node 2 back in the fold at the (possibly bumped) term
    lead = cl.leader()
    assert lead != NONE_ID
    assert len(set(cl.terms().tolist())) == 1
    assert cl.commits().min() >= 2


def test_leader_transfer():
    """TestLeaderTransferToUpToDateNode: transfer to a caught-up follower
    completes via MsgTimeoutNow; the old leader steps down."""
    cl = Cluster(n_members=3)
    cl.campaign(0)
    cl.stabilize()
    cl.propose(0, 8)
    cl.stabilize()
    # admin injects MsgTransferLeader at the leader, From = transferee (1)
    from etcd_tpu.types import MSG_TRANSFER_LEADER

    cl.inject(
        to=0, frm=1, type=MSG_TRANSFER_LEADER, term=int(cl.terms()[0])
    )
    cl.stabilize()
    assert cl.leader() == 1
    assert int(cl.terms()[1]) == 2
    assert cl.roles()[0] == ROLE_FOLLOWER


def test_read_index():
    """Linearizable read: leader confirms leadership via a heartbeat quorum
    round keyed by ctx, then surfaces a ReadState (read_only.go flow)."""
    cl = Cluster(n_members=3)
    cl.campaign(0)
    cl.stabilize()
    cl.propose(0, 4)
    cl.stabilize()
    commit_before = int(cl.commits()[0])
    ctx = cl.read_index(0)
    cl.stabilize()
    s = cl.s
    assert int(s.rs_count[0, 0]) == 1
    assert int(s.rs_ctx[0, 0, 0]) == ctx
    assert int(s.rs_index[0, 0, 0]) == commit_before


def test_read_index_forwarded_from_follower():
    """A follower's MsgReadIndex forwards to the leader and the response
    surfaces at the follower (raft.go:1458-1471)."""
    cl = Cluster(n_members=3)
    cl.campaign(0)
    cl.stabilize()
    ctx = cl.read_index(2)
    cl.stabilize()
    s = cl.s
    assert cl.get("rs_count", 2) == 1
    assert int(cl.get("rs_ctx", 2)[0]) == ctx
    assert int(cl.get("rs_index", 2)[0]) == int(cl.commits()[0])
