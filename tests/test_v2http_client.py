"""v2 REST façade + clientv2 — parseKeyRequest validation ladder
(v2http/client.go:346-527), HTTP status mapping (v2error/error.go:71-80),
the client/v2 KeysAPI/MembersAPI surface, and one over-the-wire pass
through the embedded gateway."""
import json
import urllib.error
import urllib.parse
import urllib.request

import pytest

from etcd_tpu import clientv2
from etcd_tpu.embed import Config, start_etcd
from etcd_tpu.server.kvserver import EtcdCluster
from etcd_tpu.server.v2http import V2Api
from etcd_tpu.server.v2store import (
    EcodeIndexNaN,
    EcodeInvalidField,
    EcodeKeyNotFound,
    EcodeNodeExist,
    EcodePrevValueRequired,
    EcodeRefreshTTLRequired,
    EcodeRefreshValue,
    EcodeTestFailed,
    EcodeTTLNaN,
)


@pytest.fixture(scope="module")
def ec():
    c = EtcdCluster(n_members=3)
    c.ensure_leader()
    return c


@pytest.fixture()
def api(ec):
    return V2Api(ec)


@pytest.fixture()
def cli(api):
    return clientv2.new(api)


# ------------------------------------------------ parse validation ladder

@pytest.mark.parametrize("form,code", [
    ({"prevIndex": "abc"}, EcodeIndexNaN),
    ({"waitIndex": "x"}, EcodeIndexNaN),
    ({"recursive": "yes"}, EcodeInvalidField),
    ({"sorted": "1"}, EcodeInvalidField),
    ({"prevValue": ""}, EcodePrevValueRequired),
    ({"ttl": "bad"}, EcodeTTLNaN),
    ({"prevExist": "maybe"}, EcodeInvalidField),
    ({"refresh": "true", "value": "v", "ttl": "5"}, EcodeRefreshValue),
    ({"refresh": "true"}, EcodeRefreshTTLRequired),
])
def test_parse_errors(api, form, code):
    status, body, _ = api.keys("PUT", "/pk", form)
    assert body["errorCode"] == code
    assert status == 400


def test_wait_only_with_get(api):
    status, body, _ = api.keys("PUT", "/pk", {"wait": "true"})
    assert body["errorCode"] == EcodeInvalidField


# ------------------------------------------------ status codes

def test_statuses(api):
    status, body, hdr = api.keys("PUT", "/s1", {"value": "v"})
    assert status == 201  # created
    assert body["action"] == "set"
    assert hdr["X-Etcd-Index"] >= 1
    status, body, _ = api.keys("PUT", "/s1", {"value": "v2"})
    assert status == 200  # replaced, not created
    status, body, _ = api.keys("GET", "/nope", {})
    assert status == 404
    assert body["errorCode"] == EcodeKeyNotFound
    status, body, _ = api.keys(
        "PUT", "/s1", {"value": "x", "prevValue": "bad"})
    assert status == 412
    assert body["errorCode"] == EcodeTestFailed
    status, body, _ = api.keys(
        "PUT", "/s1", {"value": "x", "prevExist": "false"})
    assert status == 412
    assert body["errorCode"] == EcodeNodeExist


def test_no_value_on_success(api):
    status, body, _ = api.keys(
        "PUT", "/nv", {"value": "secret", "noValueOnSuccess": "true"})
    assert "value" not in body["node"]


def test_quorum_get(api):
    api.keys("PUT", "/qg", {"value": "v"})
    status, body, _ = api.keys("GET", "/qg", {"quorum": "true"})
    assert body["node"]["value"] == "v"


def test_watch_longpoll_registry(api):
    status, body, _ = api.keys("GET", "/wlp", {"wait": "true"})
    assert "watch_id" in body
    wid = body["watch_id"]
    status, body, _ = api.watch_poll(wid)
    assert body == {}  # nothing yet
    api.keys("PUT", "/wlp", {"value": "v"})
    status, body, _ = api.watch_poll(wid)
    assert body["event"]["action"] == "set"
    # one-shot: consumed and deregistered; the miss carries the
    # watcher-cleared errorCode so clients know to re-watch
    status, body, _ = api.watch_poll(wid)
    assert status == 400 and body["errorCode"] == 400


def test_watch_history_immediate(api):
    api.keys("PUT", "/wh", {"value": "v"})
    idx = api._store().current_index
    status, body, _ = api.keys(
        "GET", "/wh", {"wait": "true", "waitIndex": str(idx)})
    assert body["action"] == "set"
    assert body["node"]["modifiedIndex"] == idx


def test_members_and_stats(api):
    status, body, _ = api.members("GET")
    assert len(body["members"]) == 3
    status, body, _ = api.stats("store")
    assert "setsSuccess" in body
    assert api.stats("leader")[0] == 200
    assert api.stats("bogus")[0] == 404


# ------------------------------------------------ clientv2 surface

def test_clientv2_set_get_delete(cli):
    r = cli.keys.set("/c2/a", "v1")
    assert r.action == "set"
    r = cli.keys.get("/c2/a")
    assert r.node["value"] == "v1"
    r = cli.keys.delete("/c2/a")
    assert r.action == "delete"
    with pytest.raises(clientv2.Error) as ei:
        cli.keys.get("/c2/a")
    assert ei.value.code == EcodeKeyNotFound


def test_clientv2_create_update_cas(cli):
    r = cli.keys.create("/c2/b", "v1")
    assert r.action == "create"  # prevExist=false routes to store.Create
    with pytest.raises(clientv2.Error) as ei:
        cli.keys.create("/c2/b", "v2")
    assert ei.value.code == EcodeNodeExist
    r = cli.keys.update("/c2/b", "v2")
    assert r.action == "update"
    r = cli.keys.set("/c2/b", "v3", prev_value="v2")
    assert r.action == "compareAndSwap"
    r = cli.keys.delete("/c2/b", prev_value="v3")
    assert r.action == "compareAndDelete"


def test_clientv2_create_in_order(cli):
    r1 = cli.keys.create_in_order("/c2/q", "a")
    r2 = cli.keys.create_in_order("/c2/q", "b")
    assert r1.node["key"] < r2.node["key"]
    r = cli.keys.get("/c2/q", recursive=True, sort=True)
    assert [n["value"] for n in r.node["nodes"]] == ["a", "b"]


def test_clientv2_watcher(cli):
    w = cli.keys.watcher("/c2/w", recursive=True)
    assert w.next() is None
    cli.keys.set("/c2/w/x", "1")
    ev = w.next()
    assert ev is not None and ev.node["key"] == "/c2/w/x"
    cli.keys.set("/c2/w/y", "2")
    assert w.next().node["key"] == "/c2/w/y"  # stream watcher persists
    w.cancel()


def test_clientv2_watcher_after_index(cli):
    cli.keys.set("/c2/ai", "v1")
    idx = cli.keys.get("/c2/ai").node["modifiedIndex"]
    cli.keys.set("/c2/ai", "v2")
    w = cli.keys.watcher("/c2/ai", after_index=idx)
    ev = w.next()
    assert ev.node["value"] == "v2"


def test_clientv2_members(cli):
    ms = cli.members.list()
    assert [m["id"] for m in ms] == ["0", "1", "2"]


# ------------------------------------------------ over the wire

@pytest.fixture(scope="module")
def etcd(tmp_path_factory):
    cfg = Config(cluster_size=3,
                 data_dir=str(tmp_path_factory.mktemp("v2embed")),
                 auto_tick=False)
    e = start_etcd(cfg)
    yield e
    e.close()


def _req(etcd, method, path, form=None):
    data = urllib.parse.urlencode(form or {}).encode() if form else None
    req = urllib.request.Request(
        etcd.client_url + path, data=data, method=method,
        headers={"Content-Type": "application/x-www-form-urlencoded"})
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read()), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), e.headers


def test_http_v2_roundtrip(etcd):
    st, body, hdr = _req(etcd, "PUT", "/v2/keys/wire/a",
                         {"value": "v1"})
    assert st == 201
    assert body["node"]["key"] == "/wire/a"
    assert int(hdr["X-Etcd-Index"]) >= 1
    st, body, _ = _req(etcd, "GET", "/v2/keys/wire/a")
    assert st == 200 and body["node"]["value"] == "v1"
    # query-string form on GET
    st, body, _ = _req(etcd, "GET",
                       "/v2/keys/wire?recursive=true&sorted=true")
    assert body["node"]["dir"] is True
    st, body, _ = _req(etcd, "DELETE", "/v2/keys/wire/a")
    assert st == 200 and body["action"] == "delete"
    st, body, _ = _req(etcd, "GET", "/v2/keys/wire/a")
    assert st == 404 and body["errorCode"] == EcodeKeyNotFound


def test_http_v2_members_stats(etcd):
    st, body, _ = _req(etcd, "GET", "/v2/members")
    assert st == 200 and len(body["members"]) == 3
    st, body, _ = _req(etcd, "GET", "/v2/stats/store")
    assert st == 200 and "setsSuccess" in body


def test_http_v2_watch_poll(etcd):
    st, body, _ = _req(etcd, "GET", "/v2/keys/wp?wait=true")
    wid = body["watch_id"]
    _req(etcd, "PUT", "/v2/keys/wp", {"value": "x"})
    st, body, _ = _req(etcd, "GET", f"/v2/watch_poll/{wid}")
    assert body["event"]["action"] == "set"


def test_clientv2_over_http(etcd):
    """client/v2 wire path: KeysAPI over HttpV2Api against the gateway."""
    cli = clientv2.new(etcd.client_url)
    r = cli.keys.set("/httpc2/a", "v1")
    assert r.action == "set" and r.index >= 1
    assert cli.keys.get("/httpc2/a").node["value"] == "v1"
    w = cli.keys.watcher("/httpc2/b")
    assert w.next() is None
    cli.keys.set("/httpc2/b", "x")
    ev = w.next()
    assert ev is not None and ev.node["value"] == "x"
    with pytest.raises(clientv2.Error) as ei:
        cli.keys.get("/httpc2/nope")
    assert ei.value.code == EcodeKeyNotFound
    assert len(cli.members.list()) == 3


def test_httpproxy_over_wire(etcd):
    """httpproxy failover against the live gateway + one dead endpoint."""
    from etcd_tpu.httpproxy import Director, HTTPProxy, urllib_transport

    d = Director(lambda: ["http://127.0.0.1:1", etcd.client_url],
                 failure_wait=60.0)
    p = HTTPProxy(d, urllib_transport)
    st, body, _ = p.handle("PUT", "/v2/keys/viaproxy", {"value": "pv"})
    assert st == 201
    st, body, _ = p.handle("GET", "/v2/keys/viaproxy")
    assert body["node"]["value"] == "pv"
    # the dead endpoint is now out of rotation: only one transport hop
    assert [e.url for e in d.endpoints()] == [etcd.client_url]


def test_etcdctl_v2_commands(etcd, capsys):
    """etcdctl v2 subcommand family (ctlv2 analog) over the wire."""
    from etcd_tpu import etcdctl

    ep = ["--endpoint", etcd.client_url, "v2"]
    assert etcdctl.main([*ep, "set", "/ctl/a", "v1"]) == 0
    assert etcdctl.main([*ep, "get", "/ctl/a"]) == 0
    assert capsys.readouterr().out.strip().endswith("v1")
    assert etcdctl.main([*ep, "mkdir", "/ctl/dir"]) == 0
    assert etcdctl.main([*ep, "ls", "/ctl", "--recursive"]) == 0
    out = capsys.readouterr().out
    assert "/ctl/a" in out and "/ctl/dir/" in out
    assert etcdctl.main([*ep, "update", "/ctl/a", "v2"]) == 0
    assert etcdctl.main([*ep, "rm", "/ctl/a"]) == 0
    assert etcdctl.main([*ep, "rmdir", "/ctl/dir"]) == 0
    # error path: rm of a missing key exits 1 with the v2 error line
    assert etcdctl.main([*ep, "rm", "/ctl/nope"]) == 1
    assert "100" in capsys.readouterr().err


def test_v2_ttl_over_http(api):
    """TTL params through the façade: ttl sets expiration/ttl on the
    node; refresh keeps the value while renewing the TTL; SYNC expiry
    removes the key (client.go TTL handling end-to-end)."""
    ec = api.ec

    class Clk:
        t = 5000.0

        def __call__(self):
            return Clk.t

    clk = Clk()
    old_now = ec.v2_now
    ec.v2_now = clk
    for ms in ec.members:
        ms.v2store.clock = clk
    try:
        st, body, _ = api.keys("PUT", "/ttlh/a",
                               {"value": "v", "ttl": "30"})
        assert st == 201
        assert body["node"]["ttl"] == 30
        assert "expiration" in body["node"]
        # refresh: no value, renew ttl, no watch event content change
        st, body, _ = api.keys(
            "PUT", "/ttlh/a",
            {"ttl": "60", "refresh": "true", "prevExist": "true"})
        assert st == 200
        assert body["node"]["value"] == "v"  # kept by refresh
        assert body["node"]["ttl"] == 60
        # expire via the replicated SYNC cutoff
        Clk.t += 120
        ec.v2_sync()
        st, body, _ = api.keys("GET", "/ttlh/a", {})
        assert st == 404
    finally:
        ec.v2_now = old_now


def test_etcdctl_v2_set_with_ttl(etcd, capsys):
    from etcd_tpu import etcdctl

    ep = ["--endpoint", etcd.client_url, "v2"]
    assert etcdctl.main([*ep, "set", "/ttlctl/k", "v", "--ttl",
                         "3600"]) == 0
    capsys.readouterr()
    assert etcdctl.main([*ep, "get", "/ttlctl/k"]) == 0
    assert capsys.readouterr().out.strip().endswith("v")


def test_v2_quorum_get_from_follower(api):
    """QGET routed through a follower still serves the committed value
    (the proposal forwards through consensus)."""
    api.keys("PUT", "/qf/a", {"value": "x"})
    follower = next(m for m in range(3)
                    if m != api.ec.ensure_leader())
    ev = api.ec.v2_request("QGET", "/qf/a", member=follower)
    assert ev.node["value"] == "x"
