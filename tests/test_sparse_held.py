"""HeldSparse: the chaos tier's packed delay buffer must reproduce the
dense held-buffer semantics exactly while under HELD_SLOTS messages per
sender row — pack + scatter == the old full-inbox split/merge."""
import numpy as np
import jax
import jax.numpy as jnp

from etcd_tpu.harness.chaos import (
    HELD_SLOTS,
    _held_wins,
    _merge_delayed,
    _pack_held,
    empty_held,
)
from etcd_tpu.models.engine import empty_inbox
from etcd_tpu.types import Spec

SPEC = Spec(M=5, L=16, E=1, K=2, W=4, R=2, A=2)
C = 7
S = SPEC.K * SPEC.M


def _random_traffic(seed: int, live_per_row: int):
    """A Msg in the engine's FLAT form with `live_per_row` nonempty
    slots per sender row, random small field values."""
    rng = np.random.default_rng(seed)
    out = empty_inbox(SPEC, C, wire_int16=True)
    leaves = {}
    live = np.zeros((SPEC.M, S, C), bool)
    for m in range(SPEC.M):
        for c in range(C):
            slots = rng.choice(S, size=live_per_row, replace=False)
            live[m, slots, c] = True
    for name in out.__dataclass_fields__:
        x = np.asarray(getattr(out, name)).copy()
        e = x.shape[1] // S
        vals = rng.integers(1, 100, size=(SPEC.M, S, e, C))
        x = np.where(
            np.repeat(live, e, axis=1).reshape(x.shape),
            vals.reshape(x.shape).astype(x.dtype), x)
        leaves[name] = jnp.asarray(x)
    out = out.replace(**leaves)
    # type must be nonzero exactly on live slots (liveness follows type)
    out = out.replace(type=jnp.where(jnp.asarray(live), out.type | 1,
                                     0).astype(out.type.dtype))
    return out, live


def _dense_reference(spec, out, held_dense, dm):
    """The round-4 dense split/merge, in numpy, as the oracle."""
    def bc(mask, leaf):
        if leaf.shape[1] != mask.shape[1]:
            return np.repeat(mask, leaf.shape[1] // mask.shape[1], axis=1)
        return mask

    new_held = {}
    fresh = {}
    for name in out.__dataclass_fields__:
        x = np.asarray(getattr(out, name))
        new_held[name] = np.where(bc(dm, x), x, 0)
        fresh[name] = x.copy()
    fresh["type"] = np.where(dm, 0, np.asarray(out.type))
    live = held_dense["type"] != 0
    merged = {
        name: np.where(bc(live, fresh[name]), held_dense[name],
                       fresh[name])
        for name in fresh
    }
    return merged, new_held


def test_pack_scatter_matches_dense_semantics():
    out, live = _random_traffic(0, live_per_row=2)
    rng = np.random.default_rng(1)
    dm = jnp.asarray(live & (rng.random((SPEC.M, S, C)) < 0.5))

    held0 = empty_held(SPEC, C, wire_int16=True)
    merged, new_held = _merge_delayed(SPEC, out, held0, dm)

    zero_held = {name: np.zeros_like(np.asarray(getattr(out, name)))
                 for name in out.__dataclass_fields__}
    want_merged, want_held = _dense_reference(SPEC, out, zero_held,
                                              np.asarray(dm))
    for name in out.__dataclass_fields__:
        assert np.array_equal(np.asarray(getattr(merged, name)),
                              want_merged[name]), f"merged.{name}"

    # round 2: fresh traffic + the previous round's held messages
    out2, _ = _random_traffic(2, live_per_row=2)
    no_delay = jnp.zeros((SPEC.M, S, C), bool)
    merged2, _ = _merge_delayed(SPEC, out2, new_held, no_delay)
    want_merged2, _ = _dense_reference(SPEC, out2, want_held, np.zeros(
        (SPEC.M, S, C), bool))
    for name in out.__dataclass_fields__:
        assert np.array_equal(np.asarray(getattr(merged2, name)),
                              want_merged2[name]), f"merged2.{name}"


def test_overflow_drops_extras_only():
    """More than HELD_SLOTS delayed in one row: the first HELD_SLOTS (in
    slot order) are kept, the rest drop — nothing corrupts."""
    out, live = _random_traffic(3, live_per_row=S)  # every slot live
    dm = jnp.asarray(np.ones((SPEC.M, S, C), bool))  # delay everything
    held = _pack_held(SPEC, out, dm)
    idx = np.asarray(held.idx)
    assert (idx[:, :HELD_SLOTS] == np.arange(HELD_SLOTS)[None, :, None]).all()
    # scatter back: exactly the first HELD_SLOTS slots reappear
    fresh = empty_inbox(SPEC, C, wire_int16=True)
    merged = _held_wins(SPEC, held, fresh)
    t = np.asarray(merged.type)
    assert (t[:, :HELD_SLOTS] != 0).all()
    assert (t[:, HELD_SLOTS:] == 0).all()


def test_empty_held_is_inert():
    out, _ = _random_traffic(4, live_per_row=2)
    held = empty_held(SPEC, C, wire_int16=True)
    merged = _held_wins(SPEC, held, out)
    for name in out.__dataclass_fields__:
        assert np.array_equal(np.asarray(getattr(merged, name)),
                              np.asarray(getattr(out, name))), name
