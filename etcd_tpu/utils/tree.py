"""Small pytree utilities."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def tree_where(pred, on_true, on_false):
    """Leafwise jnp.where with a scalar (or broadcastable) predicate."""
    return jax.tree.map(lambda a, b: jnp.where(pred, a, b), on_true, on_false)
