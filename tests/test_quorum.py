"""Quorum kernel vs brute-force oracles.

Mirrors the reference's property-based checks (raft/quorum/quick_test.go:122
checks CommittedIndex against an alternative implementation; majority_*.txt /
joint_*.txt datadriven cases) with a numpy oracle over randomized configs.
All cases are evaluated in a single jitted vmap call.
"""
import itertools
import random

import jax
import jax.numpy as jnp
import numpy as np

from etcd_tpu.ops.quorum import (
    committed_index,
    joint_committed_index,
    joint_vote_result,
    vote_result,
)
from etcd_tpu.types import INT32_MAX, VOTE_LOST, VOTE_PENDING, VOTE_WON

M = 7
N_CASES = 500


def oracle_committed(voters, acked):
    ids = [i for i in range(len(voters)) if voters[i]]
    n = len(ids)
    if n == 0:
        return INT32_MAX
    q = n // 2 + 1
    for idx in sorted({int(acked[i]) for i in ids} | {0}, reverse=True):
        if sum(1 for i in ids if acked[i] >= idx) >= q:
            return idx
    return 0


def oracle_vote(voters, responded, granted):
    ids = [i for i in range(len(voters)) if voters[i]]
    n = len(ids)
    if n == 0:
        return VOTE_WON
    q = n // 2 + 1
    yes = sum(1 for i in ids if responded[i] and granted[i])
    no = sum(1 for i in ids if responded[i] and not granted[i])
    if yes >= q:
        return VOTE_WON
    if yes + (n - yes - no) >= q:
        return VOTE_PENDING
    return VOTE_LOST


def rand_cases(seed):
    rng = np.random.RandomState(seed)
    return (
        rng.rand(N_CASES, M) < 0.6,        # voters
        rng.randint(0, 8, (N_CASES, M)),   # acked
        rng.rand(N_CASES, M) < 0.7,        # responded
        rng.rand(N_CASES, M) < 0.5,        # granted
        rng.rand(N_CASES, M) < 0.5,        # voters_out
    )


def test_committed_index_matches_oracle():
    voters, acked, _, _, _ = rand_cases(1)
    got = np.asarray(
        jax.jit(jax.vmap(committed_index))(
            jnp.array(voters), jnp.array(acked, jnp.int32)
        )
    )
    for i in range(N_CASES):
        assert got[i] == oracle_committed(voters[i], acked[i]), (
            voters[i],
            acked[i],
        )


def test_vote_result_matches_oracle():
    voters, _, responded, granted, _ = rand_cases(2)
    got = np.asarray(
        jax.jit(jax.vmap(vote_result))(
            jnp.array(voters), jnp.array(responded), jnp.array(granted)
        )
    )
    for i in range(N_CASES):
        assert got[i] == oracle_vote(voters[i], responded[i], granted[i])


def test_joint_committed_is_min_of_halves():
    v1, acked, _, _, v2 = rand_cases(3)
    got = np.asarray(
        jax.jit(jax.vmap(joint_committed_index))(
            jnp.array(v1), jnp.array(v2), jnp.array(acked, jnp.int32)
        )
    )
    for i in range(N_CASES):
        want = min(oracle_committed(v1[i], acked[i]), oracle_committed(v2[i], acked[i]))
        assert got[i] == want


def test_joint_vote_combines():
    v1, _, responded, granted, v2 = rand_cases(4)
    got = np.asarray(
        jax.jit(jax.vmap(joint_vote_result))(
            jnp.array(v1), jnp.array(v2), jnp.array(responded), jnp.array(granted)
        )
    )
    for i in range(N_CASES):
        r1 = oracle_vote(v1[i], responded[i], granted[i])
        r2 = oracle_vote(v2[i], responded[i], granted[i])
        if VOTE_LOST in (r1, r2):
            want = VOTE_LOST
        elif r1 == r2 == VOTE_WON:
            want = VOTE_WON
        else:
            want = VOTE_PENDING
        assert got[i] == want


def test_small_exhaustive_majorities():
    """Exhaustive check for <=5 voters and acked values in {0,1,2}."""
    cases_v, cases_a = [], []
    for n in range(6):
        for acked in itertools.product(range(3), repeat=n):
            cases_v.append([True] * n + [False] * (M - n))
            cases_a.append(list(acked) + [0] * (M - n))
    got = np.asarray(
        jax.jit(jax.vmap(committed_index))(
            jnp.array(cases_v), jnp.array(cases_a, jnp.int32)
        )
    )
    for i in range(len(cases_v)):
        assert got[i] == oracle_committed(cases_v[i], cases_a[i])
