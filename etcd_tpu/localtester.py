"""Local tester: a fault-injected live cluster under client load.

The reference's tools/local-tester runs an etcd cluster through
unreliable network bridges with a constant stream of Puts while a fault
script periodically kills members and disrupts connectivity
(tools/local-tester/{Procfile,faults.sh,bridge/}). The TPU-native analog
drives one embedded cluster (embed.start_etcd) with:

  * a constant client Put/Get stream (the benchmark-stresser role);
  * a periodic fault schedule cycling through the bridge/fault classes:
    link drops (bridge blackhole), member isolation (SIGSTOP/kill), and
    full partitions, injected through the engine keep-mask;
  * member crash + restart-from-disk (the kill/restart cycle) when a
    data dir is configured;
  * liveness/safety verification after each heal: stream errors are
    tolerated DURING faults, but the cluster must serve reads of every
    acknowledged write afterwards, and corruption_check() must pass.

Usage:
    python -m etcd_tpu.localtester [--cycles N] [--data-dir DIR]
Prints one JSON line; exit 0 iff the run is healthy.
"""
from __future__ import annotations

import json
import random

from etcd_tpu.server.kvserver import ErrTimeout, ServerError


FAULTS = ("drop_links", "isolate_member", "partition", "crash_restart")


def run_local_tester(cycles: int = 4, n_members: int = 3,
                     data_dir: str | None = None, seed: int = 0,
                     puts_per_phase: int = 8) -> dict:
    import jax.numpy as jnp

    from etcd_tpu.embed import Config, start_etcd

    rng = random.Random(seed)
    etcd = start_etcd(Config(cluster_size=n_members, auto_tick=False,
                             data_dir=data_dir))
    ec = etcd.server
    seq = [0]  # every stressed value is unique, so an identical earlier
    # write to the same key can never mask a lost later write
    acked: dict[bytes, bytes] = {}
    stats = {"puts_ok": 0, "put_errors": 0, "faults": [],
             "verify_failures": []}

    # keys whose LAST write timed out: the proposal may still commit
    # later and supersede the previously acked value — "timeout is not
    # failure", so the checker must treat them as indeterminate (the
    # reference tester's stresser does the same for context-deadline
    # errors)
    indeterminate: set[bytes] = set()

    def stress(tag: str) -> None:
        for _ in range(puts_per_phase):
            k = b"lt-%d" % rng.randrange(64)
            seq[0] += 1
            v = ("%s-%d" % (tag, seq[0])).encode()
            try:
                ec.put(k, v)
                acked[k] = v
                indeterminate.discard(k)
                stats["puts_ok"] += 1
            except ErrTimeout:
                # the proposal is in the log and may commit later,
                # superseding the acked value: indeterminate
                stats["put_errors"] += 1
                indeterminate.add(k)
            except ServerError:
                # definite rejection (no leader / quota / backpressure):
                # nothing was proposed, acked values stay verifiable
                stats["put_errors"] += 1
            etcd.tick()

    def heal_and_verify(fault: str) -> None:
        ec.cl.recover()
        for m in range(ec.M):
            if ec.members[m].crashed:
                ec.restart_member_from_disk(m)
        for _ in range(12):
            etcd.tick()
        # every acknowledged write must read back (linearizable)
        for k, v in acked.items():
            if k in indeterminate:
                continue  # a timed-out later write may have superseded it
            try:
                got = ec.range(k)["kvs"]
            except ServerError:
                stats["verify_failures"].append(f"{fault}: read {k!r} failed")
                continue
            if not got or got[0].value != v:
                stats["verify_failures"].append(
                    f"{fault}: {k!r} lost acknowledged value"
                )
        try:
            ec.corruption_check()
        except ServerError as e:
            stats["verify_failures"].append(f"{fault}: corruption: {e}")

    try:
        for cycle in range(cycles):
            fault = FAULTS[cycle % len(FAULTS)]
            if fault == "crash_restart" and data_dir is None:
                fault = "isolate_member"  # kill/restart needs a disk
            stats["faults"].append(fault)
            stress("pre")
            lead = ec.ensure_leader()
            victim = (lead + 1 + cycle) % ec.M
            if fault == "drop_links":
                # bridge-style lossy links (shared mask builder with the
                # lease chaos tier)
                from etcd_tpu.harness.chaos_lease import _Rng

                ec.cl.eng.keep_mask = jnp.asarray(
                    _Rng(seed + cycle).keep_mask(ec.M, 0.3)
                )
            elif fault == "isolate_member":
                ec.cl.isolate(victim)
            elif fault == "partition":
                others = [m for m in range(ec.M) if m != victim]
                ec.cl.partition([[victim], others])
            elif fault == "crash_restart":
                ec.sync_for_shutdown()
                ec.crash_member(victim)
            stress(fault)
            heal_and_verify(fault)

        stats["acked_keys"] = len(acked)
        stats["healthy"] = (
            not stats["verify_failures"] and stats["puts_ok"] > 0
        )
        return stats
    finally:
        # an aborted run must not leak the V3Server listener thread or
        # open member backends into the calling process
        etcd.close()


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="etcd-tpu-local-tester")
    p.add_argument("--cycles", type=int, default=4)
    p.add_argument("--members", type=int, default=3)
    p.add_argument("--data-dir", default=None)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    rep = run_local_tester(cycles=args.cycles, n_members=args.members,
                           data_dir=args.data_dir, seed=args.seed)
    print(json.dumps(rep))
    return 0 if rep["healthy"] else 1


if __name__ == "__main__":
    import sys

    from etcd_tpu.utils.cache import entrypoint_platform_setup

    # host-tier tool: C=1 steps must never dispatch over a tunnel
    entrypoint_platform_setup(force_cpu=True)
    sys.exit(main())
