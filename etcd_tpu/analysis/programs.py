"""Canonical entry programs for the trace-contract auditors.

Each registry entry reproduces one of the repo's real execution shapes —
the same builders, the same operand construction, the same donation set
the drivers use — at probe size, so the auditors in
:mod:`etcd_tpu.analysis.audit` exercise the contracts on the programs
that actually ship rather than on synthetic stand-ins:

  bare_round       engine.build_round, the flagship lockstep step
  metered_round    metrics.build_metered_round with telemetry + black box
                   over the PR-8 storage diet (packed state, deferred
                   emit, sparse outbox) — the observability pass shape
  chaos_epoch      harness.build_chaos_epoch with every plane on (delay,
                   crash, membership, telemetry, black box), donation per
                   chaos.epoch_donate_argnums — the evidence-run shape
  kv_round         engine.build_kv_round, the device-MVCC apply plane
  sharded_round    parallel.build_sharded_round over the device mesh
  shard_map_round  parallel.build_shard_map_round over the device mesh

Probe sizes are deliberately tiny (C <= 64): every audited property —
jaxpr/HLO structure, donation aliasing, collective ops — is a function
of the traced program, not of the operand magnitudes, so the small
shapes prove the same contracts the fleet-scale runs rely on.

Every program carries >= 3 labelled runtime-operand VARIANTS (same
pytree structure and avals, different values) for the one-trace audit:
the lowered program must be bit-identical across them, the discipline
that lets one traced epoch serve every fault mix.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping

__all__ = ["ProgramInstance", "PROGRAM_BUILDERS", "PROGRAM_NAMES",
           "get_program", "sharded_program", "round_value_variants",
           "epoch_value_variants"]


@dataclasses.dataclass(frozen=True)
class ProgramInstance:
    """One audited entry program: a jitted callable (donation baked in)
    plus the operand sets and the declared donation contract."""

    name: str
    jitted: Any                       # jitted callable, donation applied
    donate: tuple[int, ...]           # the declared donation set (audited)
    C: int                            # fleet width; trailing-C leaves are
    #                                   "fleet-scaled" for the completeness rule
    base: tuple                       # base operand tuple
    variants: tuple[tuple[str, tuple], ...]  # (label, args) value variants
    expected_outputs: int             # top-level output arity (D2H bound)
    # argnum -> why this fleet-scaled carry is deliberately NOT donated
    undonated_ok: Mapping[int, str] = dataclasses.field(default_factory=dict)
    # (donated argnum, live argnum) -> why a shared buffer between them
    # is tolerated (the empty_crash_state alias class)
    live_alias_ok: Mapping[tuple[int, int], str] = dataclasses.field(
        default_factory=dict)
    mesh: Any = None                  # device mesh => collectives-audited


# ---------------------------------------------------------------------------
# operand construction (mirrors __graft_entry__._fleet_inputs / run_chaos)
# ---------------------------------------------------------------------------

def _probe_spec():
    from etcd_tpu.types import Spec

    return Spec(M=3, L=16, E=1, K=2, W=2, R=2, A=2)


def round_args(spec, cfg, C: int):
    """The 9 round operands in the engine convention (clusters-minor),
    honoring the cfg's storage forms — packed state under packed_state,
    the compacted wire under compact_wire."""
    import jax.numpy as jnp

    from etcd_tpu.models.engine import empty_inbox, init_fleet

    state = init_fleet(spec, C, election_tick=cfg.election_tick)
    if cfg.packed_state:
        from etcd_tpu.models.state import pack_fleet

        state = pack_fleet(spec, state)
    inbox = empty_inbox(
        spec, C, wire_int16=cfg.wire_int16,
        compact_bound=cfg.inbox_bound if cfg.compact_wire else 0,
    )
    M, E = spec.M, spec.E
    prop_len = jnp.zeros((M, C), jnp.int32).at[0].set(1)
    prop_data = jnp.zeros((M, E, C), jnp.int32).at[0, 0].set(7)
    prop_type = jnp.zeros((M, E, C), jnp.int32)
    ri_ctx = jnp.zeros((M, C), jnp.int32)
    do_hup = jnp.zeros((M, C), jnp.bool_).at[0].set(True)
    do_tick = jnp.ones((M, C), jnp.bool_)
    keep_mask = jnp.ones((M, M, C), jnp.bool_)
    return (state, inbox, prop_len, prop_data, prop_type, ri_ctx, do_hup,
            do_tick, keep_mask)


def round_value_variants(spec, C: int, base: tuple, offset: int = 2):
    """>= 3 value-only variants of a round operand tuple (positions
    `offset`.. are prop_len, prop_data, prop_type, ri_ctx, do_hup,
    do_tick, keep_mask). Shared by the registry and driver preflight."""
    import jax.numpy as jnp

    M = spec.M
    pre, ops = base[:offset], list(base[offset:])

    def with_(i, v):
        out = list(ops)
        out[i] = v
        return pre + tuple(out)

    prop_len, prop_data = ops[0], ops[1]
    shifted = pre + (
        jnp.zeros_like(prop_len).at[M - 1].set(2),
        jnp.zeros_like(prop_data).at[M - 1, 0].set(99),
    ) + tuple(ops[2:])
    quiet = with_(4, jnp.zeros_like(ops[4]))      # do_hup off
    quiet = quiet[:offset + 5] + (jnp.zeros_like(ops[5]),) \
        + quiet[offset + 6:]                      # do_tick off too
    cut = with_(6, ops[6].at[0, 1].set(False))    # one link dropped
    return (("prop-shift", shifted), ("quiet", quiet), ("link-cut", cut))


# ---------------------------------------------------------------------------
# the programs
# ---------------------------------------------------------------------------

def _bare_round() -> ProgramInstance:
    import jax

    from etcd_tpu.models.engine import build_round
    from etcd_tpu.utils.config import RaftConfig

    spec, C = _probe_spec(), 8
    cfg = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=2)
    args = round_args(spec, cfg, C)
    return ProgramInstance(
        name="bare_round",
        jitted=jax.jit(build_round(cfg, spec), donate_argnums=(0, 1)),
        donate=(0, 1),
        C=C,
        base=args,
        variants=round_value_variants(spec, C, args),
        expected_outputs=2,
    )


def _metered_round() -> ProgramInstance:
    import dataclasses as _dc

    import jax

    from etcd_tpu.models.metrics import build_metered_round, zero_metrics
    from etcd_tpu.models.telemetry import init_telemetry
    from etcd_tpu.types import MSG_APP, MSG_APP_RESP, MSG_PROP
    from etcd_tpu.utils.config import RaftConfig

    # C=12 dodges aval collisions between probe-C-trailing leaves and
    # small fixed-size planes (the 8-slot lag histogram would otherwise
    # read as fleet-scaled at C=8)
    spec, C = _probe_spec(), 12
    # the bench steady-state storage diet (PR-8): packed fleet, deferred
    # emit, sparse-outbox-eligible message classes — the observability
    # pass must compose with the diet it meters
    cfg = _dc.replace(
        RaftConfig(pre_vote=True, check_quorum=True, max_inflight=2,
                   coalesce_commit_refresh=True),
        local_steps=("prop",),
        message_classes=(MSG_APP, MSG_APP_RESP, MSG_PROP),
        entry_classes=("normal",),
        deferred_emit=True,
        sparse_outbox=True,
        packed_state=True,
    )
    args9 = round_args(spec, cfg, C)
    from etcd_tpu.harness.chaos import empty_blackbox
    from etcd_tpu.models.engine import init_fleet

    dense = init_fleet(spec, C, election_tick=cfg.election_tick)
    tele = init_telemetry(spec, dense)
    bb = empty_blackbox(spec, dense).ring
    args = args9 + (zero_metrics(), tele, bb)
    variants = tuple(
        (label, v + (zero_metrics(), tele, bb))
        for label, v in round_value_variants(spec, C, args9)
    )
    fn = build_metered_round(cfg, spec, with_telemetry=True,
                             with_blackbox=True)
    # donation contract: the fleet carry (0, 1) plus the fleet-scaled
    # observability carries — telemetry (10: birth ring [L, C], per-node
    # lanes) and the event ring (11: [W, M, C]); both are exclusively
    # threaded, the pre-call pytree is dead once the round returns.
    # FleetMetrics (9) is a handful of scalars — donation is free to
    # skip there.
    donate = (0, 1, 10, 11)
    return ProgramInstance(
        name="metered_round",
        jitted=jax.jit(fn, donate_argnums=donate),
        donate=donate,
        C=C,
        base=args,
        variants=variants,
        expected_outputs=5,
    )


def epoch_value_variants(spec, base: tuple):
    """>= 3 value-only variants of the chaos epoch operands (positions
    10.. are drop_p, delay_p, partition_p, crash_p, down_rounds,
    keep_log, config_aware, member_p, palette, snap_boost,
    member_boost). Shared by the registry and chaos_run preflight."""
    import jax.numpy as jnp

    def with_(over: dict):
        knobs = list(base[10:])
        for i, v in over.items():
            knobs[i - 10] = v
        return base[:10] + tuple(knobs)

    f32 = jnp.float32
    crash_heavy = with_({13: f32(0.25), 14: jnp.int32(5), 19: f32(4.0)})
    palette_roll = with_({17: f32(0.1), 18: jnp.roll(base[18], 1),
                          20: f32(3.0)})
    broken_models = with_({10: f32(0.0), 11: f32(0.0),
                           15: jnp.bool_(False), 16: jnp.bool_(False)})
    return (("crash-heavy", crash_heavy), ("palette-roll", palette_roll),
            ("broken-models", broken_models))


def _chaos_epoch() -> ProgramInstance:
    import jax
    import jax.numpy as jnp

    from etcd_tpu.harness.chaos import (
        build_chaos_epoch,
        empty_blackbox,
        empty_crash_state,
        empty_held,
        epoch_donate_argnums,
        member_palette,
        zero_violations,
    )
    from etcd_tpu.models.engine import empty_inbox, init_fleet
    from etcd_tpu.models.telemetry import init_telemetry
    from etcd_tpu.utils.config import RaftConfig

    spec, C, rounds = _probe_spec(), 4, 2
    cfg = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=2)
    state = init_fleet(spec, C, election_tick=cfg.election_tick)
    M, E = spec.M, spec.E
    f32 = jnp.float32
    args = (
        state,
        empty_inbox(spec, C, wire_int16=cfg.wire_int16),
        empty_held(spec, C, cfg.wire_int16),
        empty_crash_state(state),
        jax.random.PRNGKey(0),
        jnp.zeros((M, C), jnp.int32).at[0].set(1),
        jnp.zeros((M, E, C), jnp.int32).at[0, 0].set(7),
        zero_violations(),
        init_telemetry(spec, state),
        empty_blackbox(spec, state),
        f32(0.02), f32(0.05), f32(0.1),            # drop / delay / partition
        f32(0.05), jnp.int32(3),                   # crash_p / down_rounds
        jnp.bool_(True), jnp.bool_(True),          # keep_log / config_aware
        f32(0.02), member_palette(spec, "standard"),
        f32(1.0), f32(1.0),                        # snap / member boosts
    )
    fn = build_chaos_epoch(
        cfg, spec, rounds,
        with_delay=True, with_crash=True, with_member=True,
        with_telemetry=True, with_blackbox=True,
    )
    # audit the ACCELERATOR donation contract — epoch_donate_argnums
    # returns () on cpu by design (see its docstring), which would make
    # the audit vacuous on the CPU hosts that run it
    donate = epoch_donate_argnums(True, True, True, "tpu")
    return ProgramInstance(
        name="chaos_epoch",
        jitted=jax.jit(fn, donate_argnums=donate),
        donate=donate,
        C=C,
        base=args,
        variants=epoch_value_variants(spec, args),
        expected_outputs=9,
        undonated_ok={
            3: "CrashState is a few [M, C] planes and rides as None on "
               "the crash-free tiers — donating it risks the None-"
               "donation hazard for marginal HBM (epoch_donate_argnums)",
        },
        live_alias_ok={
            (0, 3): "empty_crash_state seeds stable=state.last_index and "
                    "prev_term=state.term by reference; the TPU runtime "
                    "tolerates the donated-live alias and the CPU path "
                    "never donates (epoch_donate_argnums docstring)",
        },
    )


def _kv_round() -> ProgramInstance:
    import jax.numpy as jnp

    from etcd_tpu.device_mvcc.state import KVSpec, init_kv
    from etcd_tpu.models.engine import _jitted_kv_round
    from etcd_tpu.utils.config import RaftConfig

    spec, C = _probe_spec(), 8
    cfg = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=2)
    kvspec = KVSpec(keys=16)
    args9 = round_args(spec, cfg, C)
    kv = init_kv(kvspec, C)
    on = jnp.ones((C,), jnp.bool_)
    base = args9[:2] + (kv, on) + args9[2:]
    variants = []
    for label, v in round_value_variants(spec, C, args9):
        variants.append((label, v[:2] + (kv, on) + v[2:]))
    # do_apply is the canonical runtime-operand switch: one traced
    # program serves device-apply AND host-apply modes
    variants.append(("apply-off",
                     args9[:2] + (kv, jnp.zeros((C,), jnp.bool_))
                     + args9[2:]))
    variants.append(("apply-mixed",
                     args9[:2] + (kv, on.at[::2].set(False)) + args9[2:]))
    carry_reason = (
        "deliberately undonated: _jitted_kv_round serves interactive "
        "hosts (DeviceBackedStore, the mvcc tests) that re-read the "
        "pre-round kv/state for the do_apply=off identity contract — "
        "donation would delete the buffers they compare against"
    )
    return ProgramInstance(
        name="kv_round",
        jitted=_jitted_kv_round(cfg, spec, kvspec, 0),
        donate=(),
        C=C,
        base=base,
        variants=tuple(variants),
        expected_outputs=4,
        undonated_ok={0: carry_reason, 1: carry_reason, 2: carry_reason},
    )


def _mesh_or_none():
    import jax

    from etcd_tpu.parallel.mesh import make_fleet_mesh

    n = len(jax.devices())
    n = 8 if n >= 8 else (4 if n >= 4 else (2 if n >= 2 else 1))
    return make_fleet_mesh(n), n


def sharded_program(name: str, use_shard_map: bool, spec=None, cfg=None,
                    C: int = 64) -> ProgramInstance:
    """Parameterized sharded-round instance. The registry entries use
    the probe spec at C=64 (the test_mesh_equivalence geometry); the
    test tier passes a smaller Spec because the post-SPMD compile the
    collectives audit needs scales with program size (~2.5 min at the
    probe spec on a CPU host, measured)."""
    from etcd_tpu.parallel.mesh import (
        build_shard_map_round,
        build_sharded_round,
        shard_fleet,
    )
    from etcd_tpu.utils.config import RaftConfig

    spec = spec or _probe_spec()
    cfg = cfg or RaftConfig(pre_vote=True, check_quorum=True, max_inflight=2)
    mesh, _n = _mesh_or_none()
    args = shard_fleet(mesh, *round_args(spec, cfg, C))
    build = build_shard_map_round if use_shard_map else build_sharded_round
    variants = tuple(
        (label, shard_fleet(mesh, *v))
        for label, v in round_value_variants(spec, C, tuple(args))
    )
    return ProgramInstance(
        name=name,
        jitted=build(cfg, spec, mesh),
        donate=(0, 1),
        C=C,
        base=tuple(args),
        variants=variants,
        expected_outputs=2,
        mesh=mesh,
    )


def _sharded_round() -> ProgramInstance:
    return sharded_program("sharded_round", use_shard_map=False)


def _shard_map_round() -> ProgramInstance:
    return sharded_program("shard_map_round", use_shard_map=True)


# ---------------------------------------------------------------------------
# driver preflight factories (bench.py / chaos_run.py --preflight): the
# exact program structure the driver's knobs select, at probe operand
# shapes, with the driver's own donation sets
# ---------------------------------------------------------------------------

def bench_programs(cfg, steady_cfg, spec, telem: bool, bb_on: bool,
                   buckets: int = 8,
                   probe_C: int = 12) -> list[ProgramInstance]:
    """The program shapes a bench run executes: the steady-state timed
    scan (steady_cfg) plus, when observability is on, the met_step /
    bb_step metered rounds with the driver's positional donation sets
    (bench.py builds the same jits with the same donate_argnums)."""
    import jax

    from etcd_tpu.models.engine import init_fleet
    from etcd_tpu.models.metrics import build_metered_round, zero_metrics
    from etcd_tpu.parallel.mesh import build_scan_rounds

    out = []
    scan_args = round_args(spec, steady_cfg, probe_C)
    out.append(ProgramInstance(
        name="bench-steady-scan",
        jitted=build_scan_rounds(steady_cfg, spec, None, rounds=2),
        donate=(0, 1),
        C=probe_C,
        base=scan_args,
        variants=round_value_variants(spec, probe_C, scan_args),
        expected_outputs=2,
    ))
    if not (telem or bb_on):
        return out

    args9 = round_args(spec, cfg, probe_C)
    dense = init_fleet(spec, probe_C, election_tick=cfg.election_tick)
    from etcd_tpu.models.telemetry import init_telemetry

    tele = init_telemetry(spec, dense, buckets=buckets) if telem else None

    def metered(name, with_blackbox, tail, donate, expected):
        return ProgramInstance(
            name=name,
            jitted=jax.jit(
                build_metered_round(cfg, spec, with_telemetry=telem,
                                    with_blackbox=with_blackbox),
                donate_argnums=donate),
            donate=donate,
            C=probe_C,
            base=args9 + tail,
            variants=tuple(
                (label, v + tail)
                for label, v in round_value_variants(spec, probe_C, args9)
            ),
            expected_outputs=expected,
        )

    if telem:
        out.append(metered("bench-metered-round", False,
                           (zero_metrics(), tele), (0, 1, 10), 4))
    if bb_on:
        from etcd_tpu.models.blackbox import init_blackbox

        bb = init_blackbox(spec, dense)
        # without telemetry the tele slot rides positionally as None so
        # the ring lands at the donated arg 11 (keyword args can't donate)
        tail = (zero_metrics(), tele, bb)
        donate = (0, 1, 10, 11) if telem else (0, 1, 11)
        out.append(metered("bench-blackbox-round", True, tail, donate,
                           4 + int(telem)))
    return out


def chaos_epoch_program(cfg, spec, *, with_delay: bool = True,
                        with_crash: bool = False, with_member: bool = False,
                        with_telemetry: bool = True,
                        with_blackbox: bool = False,
                        blackbox_window: int = 32, buckets: int = 8,
                        probe_C: int = 4, rounds: int = 2) -> ProgramInstance:
    """The epoch program a chaos_run invocation will execute (same
    structure flags, probe C / rounds), with the ACCELERATOR donation
    contract from chaos.epoch_donate_argnums."""
    import jax
    import jax.numpy as jnp

    from etcd_tpu.harness.chaos import (
        build_chaos_epoch,
        empty_blackbox,
        empty_crash_state,
        empty_held,
        epoch_donate_argnums,
        member_palette,
        zero_violations,
    )
    from etcd_tpu.models.engine import empty_inbox, init_fleet
    from etcd_tpu.models.telemetry import init_telemetry

    C = probe_C
    state = init_fleet(spec, C, election_tick=cfg.election_tick)
    has_crash_carry = with_crash or with_member
    M, E = spec.M, spec.E
    f32 = jnp.float32
    args = (
        state,
        empty_inbox(spec, C, wire_int16=cfg.wire_int16),
        empty_held(spec, C, cfg.wire_int16) if with_delay else None,
        empty_crash_state(state) if has_crash_carry else None,
        jax.random.PRNGKey(0),
        jnp.zeros((M, C), jnp.int32).at[0].set(1),
        jnp.zeros((M, E, C), jnp.int32).at[0, 0].set(7),
        zero_violations(),
        init_telemetry(spec, state, buckets=buckets)
        if with_telemetry else None,
        empty_blackbox(spec, state, window=blackbox_window)
        if with_blackbox else None,
        f32(0.02), f32(0.05 if with_delay else 0.0), f32(0.1),
        f32(0.05 if has_crash_carry else 0.0),
        jnp.int32(3 if with_crash else 1),
        jnp.bool_(True), jnp.bool_(True),
        f32(0.02 if with_member else 0.0),
        # run_chaos passes a 1-slot zero palette when membership chaos
        # is structurally off (the operand must still exist)
        member_palette(spec, "standard") if with_member
        else jnp.zeros((1,), jnp.int32),
        f32(1.0), f32(1.0),
    )
    fn = build_chaos_epoch(
        cfg, spec, rounds,
        with_delay=with_delay, with_crash=with_crash,
        with_member=with_member, with_telemetry=with_telemetry,
        with_blackbox=with_blackbox,
    )
    donate = epoch_donate_argnums(with_delay, with_telemetry, with_blackbox,
                                  "tpu")
    undonated_ok = {}
    live_alias_ok = {}
    if has_crash_carry:
        undonated_ok[3] = (
            "CrashState is a few [M, C] planes and rides as None on the "
            "crash-free tiers — donating it risks the None-donation "
            "hazard for marginal HBM (epoch_donate_argnums)")
        live_alias_ok[(0, 3)] = (
            "empty_crash_state seeds stable/prev_term as references to "
            "state leaves; TPU tolerates the donated-live alias and the "
            "CPU path never donates (epoch_donate_argnums docstring)")
    return ProgramInstance(
        name="chaos-epoch",
        jitted=jax.jit(fn, donate_argnums=donate),
        donate=donate,
        C=C,
        base=args,
        variants=epoch_value_variants(spec, args),
        expected_outputs=9,
        undonated_ok=undonated_ok,
        live_alias_ok=live_alias_ok,
    )


# cheap -> expensive (the chaos epoch trace dominates; keep it last so a
# fast-failing run reports the light programs first)
PROGRAM_BUILDERS: dict[str, Callable[[], ProgramInstance]] = {
    "bare_round": _bare_round,
    "kv_round": _kv_round,
    "metered_round": _metered_round,
    "sharded_round": _sharded_round,
    "shard_map_round": _shard_map_round,
    "chaos_epoch": _chaos_epoch,
}
PROGRAM_NAMES = tuple(PROGRAM_BUILDERS)


def get_program(name: str) -> ProgramInstance:
    return PROGRAM_BUILDERS[name]()
