"""CLI for the static-analysis plane: ``python -m etcd_tpu.analysis``.

Runs level 1 (source lint, etcd_tpu/analysis/lint.py) and level 2
(trace/HLO auditors, etcd_tpu/analysis/audit.py) over the repo and the
canonical program registry, printing one ``path:line: [rule] message``
row per finding to stdout. Exit status: 0 clean, 1 findings, 2 bad
knobs (the repo-wide exit-2 validation convention).

Knobs (all validated before any heavy work starts):
  ANALYSIS_LINT      run the source lint pass              [1]
  ANALYSIS_RULES     comma list of lint rules, or "all"    [all]
  ANALYSIS_PATHS     comma list of lint targets (relative
                     to the repo root); empty = defaults   []
  ANALYSIS_AUDIT     run the trace/HLO auditors            [1]
  ANALYSIS_AUDITORS  comma list of auditors, or "all"      [all]
  ANALYSIS_PROGRAMS  comma list of registry programs, or
                     "all"                                 [all]

The audit pass needs a device backend; the CLI forces the hermetic
8-virtual-device CPU platform (the dryrun convention) unless the caller
pinned JAX_PLATFORMS. The full audit sweep traces every registry
program and compiles the sharded ones — minutes of single-core work;
``ANALYSIS_AUDIT=0`` (lint only) is the fast tier run_smoke.sh uses.
"""
from __future__ import annotations

import os
import sys
from pathlib import Path


def _repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


def _device_setup() -> None:
    """Hermetic CPU backend with 8 virtual devices for the mesh-sharded
    programs (same convention as __graft_entry__ and conftest.py). Must
    run before jax initialises a backend."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    if "JAX_PLATFORMS" not in os.environ:
        os.environ["JAX_PLATFORMS"] = "cpu"
    from etcd_tpu.utils.cache import configure_compile_cache

    configure_compile_cache(str(_repo_root()))


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    prog = "analysis"
    if argv:
        print(f"{prog}: takes no arguments (configure via ANALYSIS_* "
              f"knobs; see etcd_tpu/analysis/__main__.py)", file=sys.stderr)
        return 2

    from etcd_tpu.analysis.audit import AUDITOR_NAMES, run_audits
    from etcd_tpu.analysis.lint import DEFAULT_LINT_TARGETS, RULES, run_lint
    from etcd_tpu.analysis.programs import PROGRAM_NAMES
    from etcd_tpu.utils.knobs import env_bool, env_list, env_str, knob_error

    do_lint = env_bool(prog, "ANALYSIS_LINT", "1")
    rules = env_list(prog, "ANALYSIS_RULES", "all", tuple(RULES))
    raw_paths = env_str(prog, "ANALYSIS_PATHS", "")
    do_audit = env_bool(prog, "ANALYSIS_AUDIT", "1")
    auditors = env_list(prog, "ANALYSIS_AUDITORS", "all", AUDITOR_NAMES)
    programs = env_list(prog, "ANALYSIS_PROGRAMS", "all", PROGRAM_NAMES)

    root = _repo_root()
    targets = tuple(p.strip() for p in raw_paths.split(",") if p.strip()) \
        or DEFAULT_LINT_TARGETS
    for t in targets:
        if not (root / t).exists():
            knob_error(prog, f"ANALYSIS_PATHS: {t!r} does not exist "
                             f"under {root}")

    findings = []
    if do_lint:
        print(f"{prog}: linting {len(targets)} target(s), "
              f"{len(rules)} rule(s)", file=sys.stderr)
        findings += run_lint(root, targets, rules)
    if do_audit:
        _device_setup()
        findings += run_audits(
            programs, auditors,
            progress=lambda m: print(f"{prog}: {m}", file=sys.stderr),
        )

    for f in findings:
        print(f)
    print(f"{prog}: {len(findings)} finding(s)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
