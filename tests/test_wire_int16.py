"""RaftConfig.wire_int16 safety: every value that crosses the int16 wire
must survive the truncate/sign-extend round trip.

Regression for a chaos-found corruption: MsgSnap carried the 32-bit
applied hash in the `commit` field, which the int16 wire silently
truncated — every snapshot-restored follower adopted a wrong hash chain
and the KV_HASH checker (harness/chaos.py) flagged hash divergence at
equal applied indexes. The hash now rides split across commit (low 16
bits) and reject_hint (high 16), exact under both wire widths
(models/raft.py maybe_send_append / handle_snapshot).
"""
from __future__ import annotations

import numpy as np

from etcd_tpu.harness.cluster import Cluster
from etcd_tpu.types import Spec
from etcd_tpu.utils.config import RaftConfig

SEED_HASH = 0x12345678  # high 16 bits live


def _snapshot_catchup(wire16: bool):
    spec = Spec(M=3, L=8, E=1, K=2, W=4, R=2, A=8)
    cfg = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=4,
                     wire_int16=wire16)
    cl = Cluster(n_members=3, C=1, spec=spec, cfg=cfg)
    cl.campaign(0)
    cl.stabilize()
    assert int(cl.get("role", 0)) == 3
    for m in range(3):
        cl.set_node(m, applied_hash=np.int32(SEED_HASH))
    cl.isolate(2)
    # push the leader past the follower's reach: > L entries applied and
    # ring-compacted, so re-joining node 2 needs a snapshot
    for i in range(12):
        cl.propose(0, 100 + i)
        cl.step()
        cl.step()
    cl.stabilize()
    assert int(cl.get("snap_index", 0)) > int(cl.get("last_index", 2)), (
        "setup failed: leader did not compact past the follower"
    )
    cl.recover()
    # heartbeat ticks re-trigger the paused probe so the leader notices
    # the follower is back and ships the snapshot
    cl.stabilize(tick=True)
    return cl


def test_snapshot_hash_survives_int16_wire():
    cl = _snapshot_catchup(wire16=True)
    lh, fh = int(cl.get("applied_hash", 0)), int(cl.get("applied_hash", 2))
    assert (lh >> 16) not in (0, -1), "test vector lost its high bits"
    assert fh == lh, (
        f"restored follower hash {fh:#x} != leader hash {lh:#x}: "
        "snapshot hash mangled on the int16 wire"
    )
    assert int(cl.get("applied", 2)) == int(cl.get("applied", 0))


def test_snapshot_hash_int32_wire_unchanged():
    cl = _snapshot_catchup(wire16=False)
    lh, fh = int(cl.get("applied_hash", 0)), int(cl.get("applied_hash", 2))
    assert fh == lh
