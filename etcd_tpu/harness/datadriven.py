"""Minimal parser for cockroachdb/datadriven golden files.

The reference drives its quorum/confchange/raft suites from ``testdata/*.txt``
files in this format (see raft/quorum/datadriven_test.go:36-110 for the
argument conventions):

    # comment
    command key=(v1, v2) other=x
    ----
    expected output lines...
    <blank line ends the case>

We parse the *directives* and replay them against the TPU engine, comparing
semantic results (committed indexes, vote outcomes, final configs) rather
than byte-identical log text — the golden prose is Go-logger output, but the
decisions it records are implementation-independent.
"""
from __future__ import annotations

import dataclasses
import os
import re

REFERENCE_ROOT = "/root/reference/raft"


@dataclasses.dataclass
class Case:
    cmd: str
    args: dict[str, list[str]]
    expected: list[str]
    line: int
    input: list[str] = dataclasses.field(default_factory=list)


_ARG_RE = re.compile(r"(\w+)=\(([^)]*)\)|(\w+)=(\S+)")


def parse_directive(line: str) -> tuple[str, dict[str, list[str]]]:
    cmd, _, rest = line.partition(" ")
    args: dict[str, list[str]] = {}
    # collect bare positional tokens ("campaign 1", "stabilize 1 4") from
    # the directive with parenthesized kwarg values masked out first, so
    # 'drop=(2, 3)' doesn't leak '3)' into the positional list
    bare = re.sub(r"\w+=\([^)]*\)", "", rest)
    for tok in bare.split():
        if "=" not in tok:
            args.setdefault("_pos", []).append(tok)
    for m in _ARG_RE.finditer(rest):
        if m.group(1) is not None:
            key, raw = m.group(1), m.group(2)
            vals = [v.strip() for v in raw.split(",")] if raw.strip() else []
        else:
            key, vals = m.group(3), [m.group(4)]
        args.setdefault(key, []).extend(vals)
    return cmd, args


def parse_file(path: str) -> list[Case]:
    cases: list[Case] = []
    with open(path) as f:
        lines = f.read().splitlines()
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        if not line or line.startswith("#"):
            i += 1
            continue
        start = i
        cmd, args = parse_directive(line)
        i += 1
        # optional input lines between the directive and the ---- separator
        # (e.g. confchange's "simple\nv1 l2\n----")
        inp = []
        while i < len(lines) and lines[i].strip() != "----":
            inp.append(lines[i].strip())
            i += 1
        assert i < len(lines), f"{path}:{start + 1}: missing ---- separator"
        i += 1
        out = []
        while i < len(lines) and lines[i].strip() != "":
            out.append(lines[i])
            i += 1
        cases.append(Case(cmd, args, out, start + 1, inp))
    return cases


def reference_available() -> bool:
    return os.path.isdir(REFERENCE_ROOT)


def testdata(*parts: str) -> str:
    return os.path.join(REFERENCE_ROOT, *parts)
