"""clientv2 — the legacy v2 client (client/v2 analog).

Mirrors ``client/v2``'s surface (keys.go KeysAPI: Get/Set/Delete/Create/
CreateInOrder/Update/Watcher with the PrevExist tri-state; members.go
MembersAPI) over the in-process :class:`V2Api` gateway, the same way
``client.py`` wraps the v3 surface. Transport-level balancing/retry
collapses away in-process; ``Error`` carries the server's v2 error code
exactly like client/v2's Error type.
"""
from __future__ import annotations

from typing import Any

from etcd_tpu.server.v2http import V2Api

# PrevExist tri-state (keys.go PrevExistType)
PREV_IGNORE = None
PREV_EXIST = True
PREV_NO_EXIST = False


class Error(Exception):
    """client/v2 Error: the server's v2 error payload, client-side."""

    def __init__(self, code: int, message: str, cause: str, index: int):
        self.code = code
        self.message = message
        self.cause = cause
        self.index = index
        super().__init__(f"{code}: {message} ({cause}) [{index}]")

    @classmethod
    def from_json(cls, j: dict) -> "Error":
        return cls(j.get("errorCode", 0), j.get("message", ""),
                   j.get("cause", ""), j.get("index", 0))


class Response:
    """keys.go Response: action + node + prevNode + cluster index."""

    __slots__ = ("action", "node", "prev_node", "index")

    def __init__(self, body: dict, headers: dict):
        self.action = body.get("action")
        self.node = body.get("node")
        self.prev_node = body.get("prevNode")
        self.index = headers.get("X-Etcd-Index", 0)


def _unwrap(res: tuple[int, dict, dict]) -> Response:
    status, body, headers = res
    if "errorCode" in body:
        raise Error.from_json(body)
    if status >= 400:
        raise Error(0, body.get("error", body.get("message", "")), "",
                    headers.get("X-Etcd-Index", 0))
    return Response(body, headers)


class Watcher:
    """keys.go watcher: next() polls the gateway's parked watch."""

    def __init__(self, api: V2Api, first: dict | None, watch_id: int | None,
                 headers: dict):
        self.api = api
        self._first = first
        self.watch_id = watch_id
        self._headers = headers

    def next(self) -> Response | None:
        """One event if available, else None (the long-poll read)."""
        if self._first is not None:
            ev, self._first = self._first, None
            return Response(ev, self._headers)
        if self.watch_id is None:
            return None
        status, body, headers = self.api.watch_poll(self.watch_id)
        if "errorCode" in body:
            raise Error.from_json(body)
        if "event" not in body:
            return None
        return Response(body["event"], headers)

    def cancel(self) -> None:
        if self.watch_id is not None:
            self.api.watch_cancel(self.watch_id)
            self.watch_id = None


class KeysAPI:
    """client/v2 KeysAPI over the gateway."""

    def __init__(self, api: V2Api):
        self.api = api

    def get(self, key: str, recursive: bool = False, sort: bool = False,
            quorum: bool = False) -> Response:
        form: dict[str, Any] = {}
        if recursive:
            form["recursive"] = "true"
        if sort:
            form["sorted"] = "true"
        if quorum:
            form["quorum"] = "true"
        return _unwrap(self.api.keys("GET", key, form))

    def set(self, key: str, value: str | None = None, *,
            prev_value: str = "", prev_index: int = 0,
            prev_exist: bool | None = PREV_IGNORE,
            ttl: int | None = None, refresh: bool = False,
            dir: bool = False,
            no_value_on_success: bool = False) -> Response:
        form: dict[str, Any] = {}
        if value is not None:
            form["value"] = value
        if prev_value:
            form["prevValue"] = prev_value
        if prev_index:
            form["prevIndex"] = str(prev_index)
        if prev_exist is not PREV_IGNORE:
            form["prevExist"] = "true" if prev_exist else "false"
        if ttl is not None:
            form["ttl"] = str(ttl)
        if refresh:
            form["refresh"] = "true"
        if dir:
            form["dir"] = "true"
        if no_value_on_success:
            form["noValueOnSuccess"] = "true"
        return _unwrap(self.api.keys("PUT", key, form))

    def create(self, key: str, value: str,
               ttl: int | None = None) -> Response:
        return self.set(key, value, prev_exist=PREV_NO_EXIST, ttl=ttl)

    def create_in_order(self, dir_key: str, value: str,
                        ttl: int | None = None) -> Response:
        form: dict[str, Any] = {"value": value}
        if ttl is not None:
            form["ttl"] = str(ttl)
        return _unwrap(self.api.keys("POST", dir_key, form))

    def update(self, key: str, value: str) -> Response:
        return self.set(key, value, prev_exist=PREV_EXIST)

    def delete(self, key: str, *, prev_value: str = "",
               prev_index: int = 0, recursive: bool = False,
               dir: bool = False) -> Response:
        form: dict[str, Any] = {}
        if prev_value:
            form["prevValue"] = prev_value
        if prev_index:
            form["prevIndex"] = str(prev_index)
        if recursive:
            form["recursive"] = "true"
        if dir:
            form["dir"] = "true"
        return _unwrap(self.api.keys("DELETE", key, form))

    def watcher(self, key: str, *, after_index: int = 0,
                recursive: bool = False) -> Watcher:
        form: dict[str, Any] = {"wait": "true", "stream": "true"}
        if after_index:
            # WatcherOptions.AfterIndex: watch starts after this index
            form["waitIndex"] = str(after_index + 1)
        if recursive:
            form["recursive"] = "true"
        status, body, headers = self.api.keys("GET", key, form)
        if "errorCode" in body:
            raise Error.from_json(body)
        return Watcher(self.api, body.get("event"), body.get("watch_id"),
                       headers)


class MembersAPI:
    """client/v2 MembersAPI over the gateway."""

    def __init__(self, api: V2Api):
        self.api = api

    def list(self) -> list[dict]:
        status, body, _ = self.api.members("GET")
        return body["members"]

    def add(self, member_id: int, learner: bool = False) -> dict:
        status, body, _ = self.api.members(
            "POST", form={"id": member_id, "isLearner": learner})
        if status >= 400:
            raise Error(0, body.get("message", ""), "", 0)
        return body

    def remove(self, member_id: int) -> None:
        status, body, _ = self.api.members("DELETE", suffix=str(member_id))
        if status >= 400:
            raise Error(0, body.get("message", ""), "", 0)


class HttpV2Api:
    """Wire transport: the same (method, key, form) -> (status, body,
    headers) surface as V2Api, over real HTTP against a gateway — the
    client/v2 httpClient path (client.go) collapsed to urllib."""

    def __init__(self, base_url: str, timeout: float = 10.0, tls=None):
        from etcd_tpu.transport import resolve_client_context

        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        # transport.TLSInfo (or ssl.SSLContext) for https gateways
        self._ctx = resolve_client_context(tls)

    def _do(self, method: str, path: str, form: dict | None,
            as_json: bool = False) -> tuple[int, dict, dict]:
        import base64
        import json
        import urllib.error
        import urllib.parse
        import urllib.request

        headers = {"Content-Type": "application/json" if as_json
                   else "application/x-www-form-urlencoded"}
        form = dict(form) if form else {}
        ba = form.pop("_basic_auth", None)
        if ba:
            headers["Authorization"] = "Basic " + \
                base64.b64encode(ba.encode()).decode()
        url = self.base_url + path
        data = None
        if as_json:
            data = json.dumps(form).encode() if form else None
        elif form and method == "GET":
            url += "?" + urllib.parse.urlencode(form)
        elif form:
            data = urllib.parse.urlencode(form).encode()
        req = urllib.request.Request(
            url, data=data, method=method, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout,
                                        context=self._ctx) as r:
                body, hdrs = json.loads(r.read() or b"{}"), r.headers
                status = r.status
        except urllib.error.HTTPError as e:
            body, hdrs, status = json.loads(e.read() or b"{}"), \
                e.headers, e.code
        headers = {"X-Etcd-Index": int(hdrs.get("X-Etcd-Index", 0) or 0)}
        return status, body, headers

    def keys(self, method: str, key: str,
             form: dict | None = None) -> tuple[int, dict, dict]:
        return self._do(method, "/v2/keys" + key, form)

    def watch_poll(self, watch_id: int) -> tuple[int, dict, dict]:
        return self._do("GET", f"/v2/watch_poll/{watch_id}", None)

    def watch_cancel(self, watch_id: int) -> None:
        self._do("DELETE", f"/v2/watch_poll/{watch_id}", None)

    def members(self, method: str, suffix: str = "",
                form: dict | None = None) -> tuple[int, dict, dict]:
        return self._do(method, "/v2/members" +
                        (f"/{suffix.strip('/')}" if suffix else ""), form)

    def auth_admin(self, method: str, path: str,
                   form: dict | None = None) -> tuple[int, dict, dict]:
        # admin payloads carry JSON (role grant/revoke are nested)
        return self._do(method, "/v2/auth" + path, form, as_json=True)

    def stats(self, which: str) -> tuple[int, dict, dict]:
        return self._do("GET", f"/v2/stats/{which}", None)


class _AuthedApi:
    """Inject basic-auth creds into every request (client.go's
    Config.Username/Password carried on the transport)."""

    def __init__(self, api, username: str, password: str):
        self._api = api
        self._ba = f"{username}:{password}"

    def keys(self, method, key, form=None):
        form = dict(form or {})
        form["_basic_auth"] = self._ba
        return self._api.keys(method, key, form)

    def auth_admin(self, method, path, form=None):
        form = dict(form or {})
        form["_basic_auth"] = self._ba
        return self._api.auth_admin(method, path, form)

    def __getattr__(self, name):
        return getattr(self._api, name)


class AuthAPI:
    """client/v2 auth_user.go/auth_role.go surface over the gateway's
    /v2/auth admin routes."""

    def __init__(self, api):
        self.api = api

    def _do(self, method: str, path: str, form: dict | None = None):
        status, body, _ = self.api.auth_admin(method, path, form)
        if status >= 400:
            raise Error(0, body.get("message", ""), "", 0)
        return body

    def enabled(self) -> bool:
        return self._do("GET", "/enable")["enabled"]

    def enable(self) -> None:
        self._do("PUT", "/enable")

    def disable(self) -> None:
        self._do("DELETE", "/enable")

    def add_user(self, name: str, password: str,
                 roles: list[str] | None = None) -> dict:
        return self._do("PUT", f"/users/{name}",
                        {"password": password,
                         "roles": roles or []})

    def get_user(self, name: str) -> dict:
        return self._do("GET", f"/users/{name}")

    def list_users(self) -> list[str]:
        return self._do("GET", "/users")["users"]

    def remove_user(self, name: str) -> None:
        self._do("DELETE", f"/users/{name}")

    def grant_user(self, name: str, roles: list[str]) -> dict:
        return self._do("PUT", f"/users/{name}", {"grant": roles})

    def revoke_user(self, name: str, roles: list[str]) -> dict:
        return self._do("PUT", f"/users/{name}", {"revoke": roles})

    def add_role(self, name: str,
                 permissions: dict | None = None) -> dict:
        form = {}
        if permissions is not None:
            form["permissions"] = permissions
        return self._do("PUT", f"/roles/{name}", form)

    def get_role(self, name: str) -> dict:
        return self._do("GET", f"/roles/{name}")

    def list_roles(self) -> list[str]:
        return self._do("GET", "/roles")["roles"]

    def remove_role(self, name: str) -> None:
        self._do("DELETE", f"/roles/{name}")

    def grant_role(self, name: str, grant: dict) -> dict:
        return self._do("PUT", f"/roles/{name}", {"grant": grant})

    def revoke_role(self, name: str, revoke: dict) -> dict:
        return self._do("PUT", f"/roles/{name}", {"revoke": revoke})


class ClientV2:
    """client/v2 Client: the keys + members + auth handles. Accepts an
    in-process V2Api, an EtcdCluster (wrapped), or an endpoint URL
    string (wire transport); username/password ride every request as
    basic auth."""

    def __init__(self, ec_or_api, username: str | None = None,
                 password: str | None = None, tls=None):
        if isinstance(ec_or_api, str):
            api: Any = HttpV2Api(ec_or_api, tls=tls)
        elif isinstance(ec_or_api, (V2Api, HttpV2Api, _AuthedApi)):
            api = ec_or_api
        else:
            api = V2Api(ec_or_api)
        if username is not None:
            api = _AuthedApi(api, username, password or "")
        self.api = api
        self.keys = KeysAPI(api)
        self.members = MembersAPI(api)
        self.auth = AuthAPI(api)


def new(ec_or_api, username: str | None = None,
        password: str | None = None, tls=None) -> ClientV2:
    """client.New analog; `tls` is a transport.TLSInfo for https."""
    return ClientV2(ec_or_api, username, password, tls=tls)
