"""Durable transactional backend — the bbolt analog.

The reference keeps all applied state in a mmap'd copy-on-write B+tree
(go.etcd.io/bbolt) behind ``backend.Backend``/``BatchTx``/``ReadTx``
(server/storage/backend/backend.go:88-118): writes buffer in a batch
transaction flushed every batchInterval/batchLimit, reads see the
buffered view, and Defrag rewrites the file compactly.

The TPU-native host runtime wants the same durability contract with a
simpler mechanical design: a CRC-chained append-only record log (sharing
the WAL's frame codec, native/walcodec.cpp) replayed into an in-memory
bucket map on open. Appends are sequential (the fast path on any disk),
batch commits fsync, torn tails truncate at the first bad frame exactly
like WAL repair, and ``defrag()`` rewrites live records only. Batched
tail loss is safe by construction: the consistent-index record
(etcd_tpu/storage/schema.py) tells the replay path where to resume, the
same WAL+backend recovery contract as the reference
(cindex/cindex.go:30-38).
"""
from __future__ import annotations

import os
import struct

from etcd_tpu.storage.walcodec import get_codec

REC_PUT = 11
REC_DEL = 12

_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")


def _enc_kvrec(bucket: str, key: bytes, value: bytes | None) -> bytes:
    b = bucket.encode()
    out = _U16.pack(len(b)) + b + _U32.pack(len(key)) + key
    if value is not None:
        out += value
    return out


def _dec_kvrec(payload: bytes) -> tuple[str, bytes, bytes]:
    (bl,) = _U16.unpack_from(payload, 0)
    bucket = payload[2 : 2 + bl].decode()
    off = 2 + bl
    (kl,) = _U32.unpack_from(payload, off)
    off += 4
    key = payload[off : off + kl]
    return bucket, key, payload[off + kl :]


class Backend:
    """Bucketed durable KV with batched transactional appends."""

    def __init__(self, path: str, batch_limit: int = 128,
                 fresh: bool = False):
        """fresh=True truncates any existing file — a NEW cluster
        incarnation must not inherit a previous incarnation's records
        (reopening is only for the restart-from-disk path)."""
        self.path = path
        self.batch_limit = batch_limit  # backend.go:106-108 defaultBatchLimit
        self.codec = get_codec()
        self.data: dict[str, dict[bytes, bytes]] = {}
        self._pending: list[bytes] = []
        self._pending_ops = 0
        self._crc = 0
        self._size_logical = 0
        if os.path.exists(path):
            if fresh:
                os.remove(path)
            else:
                self._replay()
        self._f = open(path, "ab")

    # -- recovery ------------------------------------------------------------
    def _replay(self) -> None:
        with open(self.path, "rb") as f:
            buf = memoryview(f.read())
        off, crc = 0, 0
        good = 0
        while True:
            out = self.codec.decode(buf, off, crc)
            if out is None:
                break
            consumed, rtype, payload, crc = out
            off += consumed
            if rtype == REC_PUT:
                bucket, key, value = _dec_kvrec(bytes(payload))
                self.data.setdefault(bucket, {})[key] = value
            elif rtype == REC_DEL:
                bucket, key, _ = _dec_kvrec(bytes(payload))
                self.data.get(bucket, {}).pop(key, None)
            good = off
        self._crc = crc
        if good < len(buf):  # torn tail: truncate at the last good frame
            with open(self.path, "r+b") as f:
                f.truncate(good)
        self._size_logical = good

    # -- batch tx (backend.go BatchTx) ---------------------------------------
    def put(self, bucket: str, key: bytes, value: bytes) -> None:
        self.data.setdefault(bucket, {})[key] = value
        self._append(REC_PUT, _enc_kvrec(bucket, key, value))

    def delete(self, bucket: str, key: bytes) -> None:
        if self.data.get(bucket, {}).pop(key, None) is not None:
            self._append(REC_DEL, _enc_kvrec(bucket, key, None))

    def _append(self, rtype: int, payload: bytes) -> None:
        frame, self._crc = self.codec.encode(rtype, payload, self._crc)
        self._pending.append(frame)
        self._pending_ops += 1
        if self._pending_ops >= self.batch_limit:
            self.commit()

    def commit(self) -> None:
        """Flush + fsync the batch (batchTxBuffered.commit)."""
        if not self._pending:
            return
        from etcd_tpu.utils import failpoints

        # gofail beforeCommit/afterCommit analogs (backend/batch_tx.go's
        # commit path; tester/case_failpoints.go trips these mid-batch)
        failpoints.fire("backendBeforeCommit")
        blob = b"".join(self._pending)
        self._f.write(blob)
        self._f.flush()
        os.fsync(self._f.fileno())
        self._size_logical += len(blob)
        self._pending = []
        self._pending_ops = 0
        failpoints.fire("backendAfterCommit")

    # -- reads (always see the buffered view, like txReadBuffer) -------------
    def get(self, bucket: str, key: bytes) -> bytes | None:
        return self.data.get(bucket, {}).get(key)

    def range(self, bucket: str, key: bytes = b"", range_end: bytes | None = None
              ) -> list[tuple[bytes, bytes]]:
        b = self.data.get(bucket, {})
        if range_end is None:
            v = b.get(key)
            return [(key, v)] if v is not None else []
        out = [
            (k, v) for k, v in b.items()
            if k >= key and (range_end == b"\x00" or k < range_end)
        ]
        return sorted(out)

    def buckets(self) -> list[str]:
        return sorted(self.data)

    # -- maintenance ----------------------------------------------------------
    def size(self) -> int:
        """Bytes in the file (grows with history until defrag)."""
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def size_in_use(self) -> int:
        """Bytes of live records (the defragmented size)."""
        total = 0
        for bucket, kvs in self.data.items():
            for k, v in kvs.items():
                total += len(bucket) + len(k) + len(v) + 17
        return total

    def defrag(self) -> None:
        """Rewrite only live records (backend.Defrag), atomically."""
        self.commit()
        self._f.close()
        tmp = self.path + ".defrag"
        crc = 0
        with open(tmp, "wb") as f:
            for bucket in sorted(self.data):
                for key in sorted(self.data[bucket]):
                    frame, crc = self.codec.encode(
                        REC_PUT,
                        _enc_kvrec(bucket, key, self.data[bucket][key]),
                        crc,
                    )
                    f.write(frame)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._crc = crc
        self._size_logical = os.path.getsize(self.path)
        self._f = open(self.path, "ab")

    def close(self) -> None:
        self.commit()
        self._f.close()
