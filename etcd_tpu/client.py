"""Client façade — the clientv3 analog.

Mirrors ``client/v3``'s surface (client.go / kv.go / watch.go / lease.go /
txn.go op-builders) over an in-process :class:`EtcdCluster`, the way the
reference embeds a client via `api/v3client`. Namespacing (client/v3/
namespace) is a constructor option; retry/balancer machinery collapses away
because transport faults surface as engine-level mask faults, not RPC
errors.
"""
from __future__ import annotations

import dataclasses
from typing import Any

from etcd_tpu.server.kvserver import Compare, EtcdCluster, Op


def prefix_range_end(prefix: bytes) -> bytes:
    """clientv3.GetPrefixRangeEnd (client/v3/op.go): increment the last
    byte that can be incremented; all-0xff prefixes scan to end."""
    end = bytearray(prefix)
    for i in range(len(end) - 1, -1, -1):
        if end[i] < 0xFF:
            end[i] += 1
            return bytes(end[: i + 1])
    return b"\x00"


@dataclasses.dataclass
class TxnBuilder:
    """clientv3.Txn: If(...).Then(...).Else(...).Commit()."""

    client: "Client"
    _compare: list[Compare] = dataclasses.field(default_factory=list)
    _success: list[Op] = dataclasses.field(default_factory=list)
    _failure: list[Op] = dataclasses.field(default_factory=list)

    def if_(self, *cmps: Compare) -> "TxnBuilder":
        self._compare.extend(cmps)
        return self

    def then(self, *ops: Op) -> "TxnBuilder":
        self._success.extend(ops)
        return self

    def else_(self, *ops: Op) -> "TxnBuilder":
        self._failure.extend(ops)
        return self

    def commit(self) -> dict:
        return self.client.ec.txn(
            self._compare,
            [self.client._ns_op(o) for o in self._success],
            [self.client._ns_op(o) for o in self._failure],
            token=self.client.token,
        )


class Client:
    def __init__(self, ec: EtcdCluster, namespace: bytes = b"",
                 token: str | None = None):
        self.ec = ec
        self.ns = namespace
        self.token = token

    # -- namespacing (client/v3/namespace) -----------------------------------
    def _key(self, key: bytes) -> bytes:
        return self.ns + key

    def _range_end(self, key: bytes, range_end: bytes | None):
        if range_end is None:
            return None
        if range_end == b"\x00":
            return prefix_range_end(self.ns) if self.ns else b"\x00"
        return self.ns + range_end

    def _ns_op(self, op: Op) -> Op:
        return dataclasses.replace(
            op, key=self._key(op.key),
            range_end=self._range_end(op.key, op.range_end),
        )

    def _strip(self, kvs):
        """Return prefix-stripped COPIES — range hands back the store's own
        KeyValue objects, which must stay immutable."""
        if not self.ns:
            return kvs
        return [
            dataclasses.replace(kv, key=kv.key[len(self.ns):]) for kv in kvs
        ]

    # -- KV ------------------------------------------------------------------
    def put(self, key: bytes, value: bytes, lease: int = 0,
            prev_kv: bool = False) -> dict:
        return self.ec.put(self._key(key), value, lease, prev_kv, self.token)

    def get(self, key: bytes, rev: int = 0, serializable: bool = False,
            member: int | None = None):
        res = self.ec.range(
            self._key(key), rev=rev, serializable=serializable, member=member,
            token=self.token,
        )
        kvs = self._strip(res["kvs"])
        return kvs[0] if kvs else None

    def get_range(self, key: bytes, range_end: bytes | None = None, **kw):
        res = self.ec.range(
            self._key(key), self._range_end(key, range_end),
            token=self.token, **kw,
        )
        res["kvs"] = self._strip(res["kvs"])
        return res

    def get_prefix(self, prefix: bytes, **kw):
        return self.get_range(prefix, prefix_range_end(prefix), **kw)

    def delete(self, key: bytes, range_end: bytes | None = None,
               prev_kv: bool = False):
        return self.ec.delete_range(
            self._key(key), self._range_end(key, range_end), prev_kv, self.token
        )

    def delete_prefix(self, prefix: bytes):
        return self.delete(prefix, prefix_range_end(prefix))

    def compact(self, rev: int):
        return self.ec.compact(rev)

    def txn(self) -> TxnBuilder:
        return TxnBuilder(self)

    # compare builders (client/v3/compare.go)
    def compare_value(self, key, result, value) -> Compare:
        return Compare(self._key(key), "value", result, value)

    def compare_version(self, key, result, version) -> Compare:
        return Compare(self._key(key), "version", result, version)

    def compare_create(self, key, result, rev) -> Compare:
        return Compare(self._key(key), "create", result, rev)

    def compare_mod(self, key, result, rev) -> Compare:
        return Compare(self._key(key), "mod", result, rev)

    # -- watch ---------------------------------------------------------------
    def watch(self, key: bytes, range_end: bytes | None = None,
              start_rev: int = 0, prev_kv: bool = False,
              member: int | None = None, filters: tuple = (),
              progress_notify: bool = False, fragment: bool = False):
        """clientv3 WatchCreateRequest options: `filters` drops event types
        ("put"/"delete" — WithFilterPut/WithFilterDelete), `progress_notify`
        = WithProgressNotify, `fragment` = WithFragment."""
        m = member if member is not None else self.ec.ensure_leader()
        w = self.ec.watch(
            m, self._key(key), self._range_end(key, range_end), start_rev,
            prev_kv, fragment=fragment, progress_notify=progress_notify,
            filters=filters,
        )
        return _WatchHandle(self, m, w.id)

    def watch_prefix(self, prefix: bytes, **kw):
        return self.watch(prefix, prefix_range_end(prefix), **kw)

    # -- lease ---------------------------------------------------------------
    def lease_grant(self, lease_id: int, ttl: int):
        return self.ec.lease_grant(lease_id, ttl)

    def lease_revoke(self, lease_id: int):
        return self.ec.lease_revoke(lease_id)

    def lease_keepalive(self, lease_id: int):
        return self.ec.lease_keepalive(lease_id)

    # -- auth ----------------------------------------------------------------
    def login(self, name: str, password: str) -> "Client":
        return Client(self.ec, self.ns, self.ec.authenticate(name, password))


@dataclasses.dataclass
class _WatchHandle:
    client: Client
    member: int
    watch_id: int

    def request_progress(self) -> int | None:
        """clientv3 Watcher.RequestProgress: current revision once this
        watcher is fully synced, else None."""
        return self.client.ec.watch_progress(self.member, self.watch_id)

    def events(self):
        evs = self.client.ec.watch_events(self.member, self.watch_id)
        if self.client.ns:
            evs = [
                dataclasses.replace(
                    e, kv=dataclasses.replace(
                        e.kv, key=e.kv.key[len(self.client.ns):]
                    )
                )
                if e.kv.key.startswith(self.client.ns) else e
                for e in evs
            ]
        return evs

    def cancel(self) -> bool:
        return self.client.ec.cancel_watch(self.member, self.watch_id)


# --------------------------------------------------------- wire transport

class RemoteError(Exception):
    """A gateway error response (the clientv3 rpctypes error analog)."""


class RemoteClient:
    """clientv3 over the wire: the JSON/HTTP gateway transport analog of
    the reference's gRPC client path (client/v3/client.go dial +
    credentials). The in-process :class:`Client` drives EtcdCluster
    directly; this one reaches a server in another process — over HTTPS
    with CA verification, mutual TLS, or cert-CN identity — using the
    same endpoints etcdctl speaks.

    `tls` is a :class:`etcd_tpu.transport.TLSInfo` (or a prebuilt
    ``ssl.SSLContext``): trusted_ca_file verifies the server cert,
    client_cert/key enable mutual TLS (and cert-CN auth when the server
    requires client certs)."""

    def __init__(self, endpoint: str, token: str | None = None,
                 tls=None, timeout: float | None = 10.0):
        from etcd_tpu.transport import resolve_client_context

        self.endpoint = endpoint.rstrip("/")
        self.token = token
        self.timeout = timeout  # None = block (CLI snapshot saves etc.)
        self._ctx = resolve_client_context(tls)

    # ---- transport
    def call(self, path: str, body: dict) -> dict:
        import json
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            self.endpoint + path, data=json.dumps(body).encode(),
            method="POST",
            headers={
                "Content-Type": "application/json",
                **({"Authorization": self.token} if self.token else {}),
            })
        try:
            with urllib.request.urlopen(
                    req, timeout=self.timeout, context=self._ctx) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            out = e.read()
            try:
                parsed = json.loads(out or b"{}")
                msg = parsed.get("error", "") if isinstance(
                    parsed, dict) else str(parsed)
            except json.JSONDecodeError:
                msg = out.decode(errors="replace")
            raise RemoteError(msg or f"HTTP {e.code}") from None

    def get_raw(self, path: str) -> bytes:
        """GET an etcdhttp endpoint (/health, /metrics, snapshots)
        through the same TLS context as the JSON calls."""
        import urllib.request

        with urllib.request.urlopen(self.endpoint + path,
                                    timeout=self.timeout,
                                    context=self._ctx) as r:
            return r.read()

    @staticmethod
    def _b64(v: bytes) -> str:
        import base64

        return base64.b64encode(v).decode()

    @staticmethod
    def _unb64(v: str | None) -> bytes:
        import base64

        return base64.b64decode(v) if v else b""

    # ---- auth
    def login(self, name: str, password: str) -> "RemoteClient":
        out = self.call("/v3/auth/authenticate",
                        {"name": name, "password": password})
        self.token = out["token"]
        return self

    # ---- kv
    def put(self, key: bytes, value: bytes, lease: int = 0) -> dict:
        body: dict = {"key": self._b64(key), "value": self._b64(value)}
        if lease:
            body["lease"] = str(lease)
        return self.call("/v3/kv/put", body)

    def get(self, key: bytes) -> bytes | None:
        res = self.call("/v3/kv/range", {"key": self._b64(key)})
        kvs = res.get("kvs", [])
        return self._unb64(kvs[0].get("value")) if kvs else None

    def get_prefix(self, prefix: bytes) -> list[tuple[bytes, bytes]]:
        res = self.call("/v3/kv/range", {
            "key": self._b64(prefix),
            "range_end": self._b64(prefix_range_end(prefix)),
        })
        return [(self._unb64(kv.get("key")), self._unb64(kv.get("value")))
                for kv in res.get("kvs", [])]

    def delete(self, key: bytes, range_end: bytes | None = None) -> int:
        body = {"key": self._b64(key)}
        if range_end:
            body["range_end"] = self._b64(range_end)
        return int(self.call("/v3/kv/deleterange", body).get("deleted", 0))

    # ---- lease
    def lease_grant(self, lease_id: int, ttl: int) -> dict:
        return self.call("/v3/lease/grant",
                         {"ID": str(lease_id), "TTL": str(ttl)})

    def lease_keepalive(self, lease_id: int) -> dict:
        return self.call("/v3/lease/keepalive", {"ID": str(lease_id)})

    def lease_revoke(self, lease_id: int) -> dict:
        return self.call("/v3/lease/revoke", {"ID": str(lease_id)})

    # ---- watch (create + poll, the gateway's long-poll stream stand-in)
    def watch(self, key: bytes, prefix: bool = False,
              start_rev: int = 0) -> "RemoteWatch":
        c: dict = {"key": self._b64(key)}
        if prefix:
            c["range_end"] = self._b64(prefix_range_end(key))
        if start_rev:
            c["start_revision"] = str(start_rev)
        out = self.call("/v3/watch", {"create_request": c})
        return RemoteWatch(self, int(out["watch_id"]))

    # ---- maintenance
    def status(self) -> dict:
        return self.call("/v3/maintenance/status", {})

    def member_list(self) -> dict:
        return self.call("/v3/cluster/member/list", {})


@dataclasses.dataclass
class RemoteWatch:
    client: RemoteClient
    watch_id: int

    def events(self) -> list[tuple[str, bytes, bytes]]:
        """Drain pending events as (type, key, value) triples."""
        out = self.client.call("/v3/watch", {
            "poll_request": {"watch_id": str(self.watch_id)}})
        return [(e["type"],
                 RemoteClient._unb64(e["kv"].get("key")),
                 RemoteClient._unb64(e["kv"].get("value")))
                for e in out.get("events", [])]

    def cancel(self) -> bool:
        out = self.client.call("/v3/watch", {
            "cancel_request": {"watch_id": str(self.watch_id)}})
        return bool(out.get("canceled"))
