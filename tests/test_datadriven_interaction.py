"""Replay ALL reference interaction goldens (raft/testdata/*.txt) against
the TPU engine through the InteractionEnv command language
(raft/rafttest/interaction_env_handler.go:29-146, interaction_test.go:34).

Comparison is semantic: structural output (Ready blocks, message lines,
entries, status, raft-log) is compared verbatim; logger lines are reduced
to a curated event vocabulary (role transitions, configuration switches,
snapshot restores, newRaft boots) that both sides must produce in the
same order, while incidental Go-logger prose (vote tallies, probe/pause
DEBUG chatter) is dropped from both sides identically.
"""
from __future__ import annotations

import os
import re

import pytest

from etcd_tpu.harness.datadriven import parse_file, reference_available, testdata
from etcd_tpu.harness.interaction import InteractionEnv

GOLDENS = [
    "campaign.txt",
    "campaign_learner_must_vote.txt",
    "confchange_v1_add_single.txt",
    "confchange_v1_remove_leader.txt",
    "confchange_v2_add_double_auto.txt",
    "confchange_v2_add_double_implicit.txt",
    "confchange_v2_add_single_auto.txt",
    "confchange_v2_add_single_explicit.txt",
    "probe_and_replicate.txt",
    "snapshot_succeed_via_app_resp.txt",
]

_LOG_TOKENS = ("INFO", "DEBUG", "WARN", "ERROR", "FATAL")

# Curated logger events: both sides must agree on these exactly.
_CURATED = [
    ("become", re.compile(
        r"(?:INFO|DEBUG) (\d+) became "
        r"(follower|pre-candidate|candidate|leader) at term (\d+)$")),
    ("switch", re.compile(
        r"(?:INFO|DEBUG) (\d+) switched to configuration (.+)$")),
    ("newraft", re.compile(r"(?:INFO|DEBUG) newRaft (\d+) \[(.+)\]$")),
    ("restored", re.compile(
        r"(?:INFO|DEBUG) (\d+) \[(.+)\] restored snapshot \[(.+)\]$")),
]


def normalize(text: str) -> list[tuple]:
    events: list[tuple] = []
    for raw in text.split("\n"):
        line = raw.strip()
        if not line:
            continue
        if line.split(" ", 1)[0] in _LOG_TOKENS:
            for kind, rx in _CURATED:
                m = rx.match(line)
                if m:
                    events.append((kind,) + m.groups())
                    break
            continue
        if line in ("ok", "ok (quiet)"):
            # bare acknowledgements carry no semantic content: a golden
            # block holding only non-curated logger prose normalizes to
            # the same empty event list as our "ok"
            continue
        events.append(("line", re.sub(r"\s+", " ", line)))
    return events


@pytest.mark.skipif(not reference_available(), reason="no reference checkout")
@pytest.mark.parametrize("fname", GOLDENS)
def test_interaction_golden(fname):
    env = InteractionEnv()
    for case in parse_file(testdata("testdata", fname)):
        out = env.handle(case)
        exp = "\n".join(case.expected)
        got, want = normalize(out), normalize(exp)
        assert got == want, (
            f"{fname}:{case.line} ({case.cmd} {case.args})\n"
            f"-- expected --\n{exp}\n-- actual --\n{out}"
        )
