"""Benchmark CLI: the tools/benchmark analog.

The reference ships a cobra load generator (tools/benchmark/cmd: put,
range, txn-put, txn-mixed, lease, watch, watch-latency, ...) reporting
latency histograms and throughput via pkg/report. This drives the same
workloads over the v3 JSON/HTTP wire against any endpoint (a live
etcd_tpu.etcdmain process or the reference's gateway) and prints a
pkg/report-style summary.

Usage:
    python -m etcd_tpu.benchmark --endpoint http://127.0.0.1:2379 \
        put --total 1000 --key-size 8 --val-size 32
    python -m etcd_tpu.benchmark range --total 500 --serializable
    python -m etcd_tpu.benchmark txn-put --total 200
    python -m etcd_tpu.benchmark watch-latency --total 100
"""
from __future__ import annotations

import argparse
import base64
import json
import math
import os
import sys
import time
import urllib.request


def b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


class Wire:
    def __init__(self, endpoint: str):
        self.endpoint = endpoint.rstrip("/")

    def call(self, path: str, body: dict) -> dict:
        req = urllib.request.Request(
            self.endpoint + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req) as resp:
            return json.loads(resp.read())


class Report:
    """pkg/report analog: latency summary + histogram."""

    def __init__(self):
        self.lat: list[float] = []

    def add(self, seconds: float) -> None:
        self.lat.append(seconds)

    def render(self, total_s: float) -> str:
        n = len(self.lat)
        if not n:
            return "no samples"
        lat = sorted(self.lat)
        pct = lambda p: lat[min(n - 1, int(math.ceil(p * n)) - 1)] * 1000
        lines = [
            "",
            "Summary:",
            f"  Total:\t{total_s:.4f} secs.",
            f"  Slowest:\t{lat[-1] * 1000:.4f} ms.",
            f"  Fastest:\t{lat[0] * 1000:.4f} ms.",
            f"  Average:\t{sum(lat) / n * 1000:.4f} ms.",
            f"  Requests/sec:\t{n / total_s:.4f}",
            "",
            "Latency distribution:",
        ]
        for p in (0.10, 0.25, 0.50, 0.75, 0.90, 0.95, 0.99):
            lines.append(f"  {int(p * 100)}% in {pct(p):.4f} ms.")
        # coarse histogram (pkg/report prints one too)
        lo, hi = lat[0], lat[-1]
        buckets = 8
        width = (hi - lo) / buckets or 1e-9
        counts = [0] * buckets
        for v in lat:
            counts[min(buckets - 1, int((v - lo) / width))] += 1
        lines.append("")
        lines.append("Response time histogram:")
        peak = max(counts)
        for i, c in enumerate(counts):
            bar = "|" + "-" * int(40 * c / peak) if peak else "|"
            lines.append(f"  {(lo + i * width) * 1000:8.4f} ms [{c}]\t{bar}")
        return "\n".join(lines)


def _timed(rep: Report, fn) -> None:
    t0 = time.perf_counter()
    fn()
    rep.add(time.perf_counter() - t0)


def run_put(w: Wire, args) -> Report:
    rep = Report()
    for i in range(args.total):
        key = os.urandom(max(args.key_size // 2, 1)).hex().encode()
        val = b"v" * args.val_size
        _timed(rep, lambda: w.call(
            "/v3/kv/put", {"key": b64(b"bench/" + key), "value": b64(val)}
        ))
    return rep


def run_range(w: Wire, args) -> Report:
    w.call("/v3/kv/put", {"key": b64(b"bench/r"), "value": b64(b"x")})
    rep = Report()
    body = {"key": b64(b"bench/r")}
    if args.serializable:
        body["serializable"] = True
    for _ in range(args.total):
        _timed(rep, lambda: w.call("/v3/kv/range", dict(body)))
    return rep


def run_txn_put(w: Wire, args) -> Report:
    rep = Report()
    for i in range(args.total):
        key = b64(b"bench/t%d" % (i % 64))
        body = {
            "compare": [],
            "success": [{"request_put": {"key": key,
                                         "value": b64(b"v" * args.val_size)}}],
            "failure": [],
        }
        _timed(rep, lambda: w.call("/v3/kv/txn", body))
    return rep


def run_watch_latency(w: Wire, args) -> Report:
    """Time from put to the event arriving at a watcher
    (tools/benchmark/cmd/watch_latency.go)."""
    res = w.call("/v3/watch", {"create_request": {"key": b64(b"bench/w")}})
    wid = res["watch_id"]
    rep = Report()
    for i in range(args.total):
        t0 = time.perf_counter()
        w.call("/v3/kv/put", {"key": b64(b"bench/w"),
                              "value": b64(b"%d" % i)})
        while True:
            evs = w.call("/v3/watch",
                         {"poll_request": {"watch_id": wid}})["events"]
            if evs:
                break
        rep.add(time.perf_counter() - t0)
    w.call("/v3/watch", {"cancel_request": {"watch_id": wid}})
    return rep


def run_txn_mixed(w: Wire, args) -> Report:
    """txn_mixed.go: a mixed load of txn-put and txn-range at
    --rw-ratio (reads per write)."""
    w.call("/v3/kv/put", {"key": b64(b"bench/m0"), "value": b64(b"x")})
    rep = Report()
    reads = writes = 0
    for i in range(args.total):
        # keep the running mix at --rw-ratio reads per write, including
        # fractional ratios (0.5 = two writes per read)
        if reads >= args.rw_ratio * writes:
            writes += 1
            body = {"success": [{"request_put": {
                "key": b64(b"bench/m%d" % (i % 64)),
                "value": b64(b"v" * args.val_size)}}]}
        else:
            reads += 1
            body = {"success": [{"request_range": {
                "key": b64(b"bench/m0")}}]}
        _timed(rep, lambda: w.call("/v3/kv/txn", body))
    return rep


def run_stm(w: Wire, args) -> Report:
    """stm.go: optimistic read-modify-write transactions with conflict
    retry (the clientv3/concurrency STM loop collapsed to a
    compare-mod-revision txn)."""
    nkeys = max(1, args.stm_keys)
    for i in range(nkeys):
        w.call("/v3/kv/put", {"key": b64(b"stm/%d" % i),
                              "value": b64(b"0")})
    rep = Report()
    for i in range(args.total):
        key = b64(b"stm/%d" % (i % nkeys))

        def rmw():
            while True:
                got = w.call("/v3/kv/range", {"key": key})
                kv = got["kvs"][0]
                mod = kv["mod_revision"]
                n = int(base64.b64decode(kv["value"]) or b"0")
                res = w.call("/v3/kv/txn", {
                    "compare": [{"key": key, "target": "MOD",
                                 "result": "EQUAL",
                                 "mod_revision": mod}],
                    "success": [{"request_put": {
                        "key": key, "value": b64(b"%d" % (n + 1))}}],
                })
                if res.get("succeeded"):
                    return

        _timed(rep, rmw)
    return rep


def run_lease(w: Wire, args) -> Report:
    """lease.go: lease keepalive throughput over granted leases."""
    # random ID base: reruns after an interrupted run (leases never
    # revoked) and concurrent bench processes must not collide
    base = int.from_bytes(os.urandom(4), "big") << 8
    ids = []
    for i in range(min(args.total, 64)):
        out = w.call("/v3/lease/grant",
                     {"ID": str(base + i), "TTL": "60"})
        ids.append(out["ID"])
    rep = Report()
    for i in range(args.total):
        lid = ids[i % len(ids)]
        _timed(rep, lambda: w.call("/v3/lease/keepalive", {"ID": lid}))
    for lid in ids:
        w.call("/v3/lease/revoke", {"ID": lid})
    return rep


def run_watch(w: Wire, args) -> Report:
    """watch.go: watcher creation throughput, then events/sec delivered
    to --watchers watchers over --total puts."""
    rep = Report()
    wids = []
    for i in range(args.watchers):
        def create(i=i):
            res = w.call("/v3/watch", {"create_request": {
                "key": b64(b"bench/wf")}})
            wids.append(res["watch_id"])

        _timed(rep, create)
    delivered = 0
    t0 = time.perf_counter()
    for i in range(args.total):
        w.call("/v3/kv/put", {"key": b64(b"bench/wf"),
                              "value": b64(b"%d" % i)})
    for wid in wids:
        while True:
            evs = w.call("/v3/watch", {"poll_request":
                                       {"watch_id": wid}})["events"]
            if not evs:
                break
            delivered += len(evs)
    dt = time.perf_counter() - t0
    create_s = sum(rep.lat) or 1e-9
    print(f"watchers: {len(wids)} created at "
          f"{len(wids) / create_s:.1f}/sec  events delivered: "
          f"{delivered} ({delivered / dt:.1f} events/sec)")
    print("(Summary below = watcher-creation latencies; its "
          "Requests/sec divides by the whole run)")
    for wid in wids:
        w.call("/v3/watch", {"cancel_request": {"watch_id": wid}})
    return rep


def run_watch_get(w: Wire, args) -> Report:
    """watch_get.go: --watchers watchers created at an OLD revision (so
    each must catch up through history) racing serializable gets — the
    unsynced-watcher contention bench."""
    first = w.call("/v3/kv/put", {"key": b64(b"bench/wg"),
                                  "value": b64(b"0")})
    start_rev = int(first["header"].get("revision", 1))
    for i in range(args.watch_events):
        w.call("/v3/kv/put", {"key": b64(b"bench/wg"),
                              "value": b64(b"%d" % i)})
    wids = [w.call("/v3/watch", {"create_request": {
        "key": b64(b"bench/wg"),
        "start_revision": str(start_rev)}})["watch_id"]
        for _ in range(args.watchers)]
    rep = Report()  # get latency while watchers sync
    for i in range(args.total):
        _timed(rep, lambda: w.call(
            "/v3/kv/range", {"key": b64(b"bench/wg"),
                             "serializable": True}))
    caught = 0
    for wid in wids:
        while True:
            evs = w.call("/v3/watch", {"poll_request":
                                       {"watch_id": wid}})["events"]
            if not evs:
                break
            caught += len(evs)
        w.call("/v3/watch", {"cancel_request": {"watch_id": wid}})
    print(f"watchers: {len(wids)}  catch-up events: {caught}")
    return rep


def run_mvcc_put(_w, args) -> Report:
    """mvcc-put.go: the DIRECT storage bench — puts straight into a
    host MVCC store with no consensus, wire, or JSON in the path.
    Isolates the host apply layer's ceiling (the honest denominator
    for wire-path numbers)."""
    from etcd_tpu.server.mvcc import MVCCStore

    st = MVCCStore()
    val = b"v" * args.val_size
    keys = [os.urandom(max(args.key_size // 2, 1)).hex().encode()
            for _ in range(args.total)]
    rep = Report()
    for k in keys:
        def one_put(k=k):
            txn = st.write_txn()
            txn.put(k, val)
            txn.end()

        _timed(rep, one_put)
    return rep


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="benchmark-tpu")
    p.add_argument("--endpoint", default="http://127.0.0.1:2379")
    sub = p.add_subparsers(dest="cmd", required=True)
    for name in ("put", "range", "txn-put", "txn-mixed", "stm", "lease",
                 "watch", "watch-get", "watch-latency", "mvcc-put"):
        s = sub.add_parser(name)
        s.add_argument("--total", type=int, default=100)
        s.add_argument("--key-size", type=int, default=8)
        s.add_argument("--val-size", type=int, default=32)
        if name == "range":
            s.add_argument("--serializable", action="store_true")
        if name == "txn-mixed":
            s.add_argument("--rw-ratio", type=float, default=1.0)
        if name == "stm":
            s.add_argument("--stm-keys", type=int, default=8)
        if name in ("watch", "watch-get"):
            s.add_argument("--watchers", type=int, default=10)
        if name == "watch-get":
            s.add_argument("--watch-events", type=int, default=50)
    args = p.parse_args(argv)
    w = Wire(args.endpoint)
    runner = {
        "put": run_put, "range": run_range, "txn-put": run_txn_put,
        "txn-mixed": run_txn_mixed, "stm": run_stm, "lease": run_lease,
        "watch": run_watch, "watch-get": run_watch_get,
        "watch-latency": run_watch_latency, "mvcc-put": run_mvcc_put,
    }[args.cmd]
    t0 = time.perf_counter()
    rep = runner(w, args)
    print(rep.render(time.perf_counter() - t0))
    return 0


if __name__ == "__main__":
    from etcd_tpu.utils.cache import entrypoint_platform_setup

    entrypoint_platform_setup()
    sys.exit(main())
