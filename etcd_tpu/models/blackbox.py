"""Black-box forensics plane: per-group event rings + post-mortem decode.

PR 9's telemetry made the fleet *measurable* (aggregate histograms, a
flight recorder, /metrics) but not *diagnosable*: when a recovery
checker fires at 262k groups, cumulative counters cannot say WHICH group
failed or what its members did in the rounds before the violation. This
module is the aviation-style answer — a black-box flight recorder:

  * :class:`EventRing` — a pytree riding BESIDE the fleet state exactly
    like FleetTelemetry: ``ring[W, M, C]`` holds one bit-packed i32
    EVENT WORD per (round window slot, member, group), where W is a
    build-time window (~32 rounds). Each word packs the member's role,
    role/term transitions, commit/applied frontier deltas, per-class
    message send/receive activity, crash/restart/down flags, conf-change
    applies and snapshot installs — everything needed to read a per-round
    timeline of a group's last W rounds.
  * :func:`blackbox_update` — ONE pure read-only reduction of (pre,
    post) round states plus the consumed/emitted wire; shared by the
    metered round (models/metrics.py build_metered_round), the chaos
    epoch scan (harness/chaos.py) and the serving Cluster, so a word
    means the same thing everywhere. It never feeds back: a ring-on
    round is bit-identical in state AND wire to the ring-off round
    (tests/test_telemetry_blackbox.py proves it, incl. packed_state /
    sparse_outbox and the crash-chaos epoch program).
  * on-violation extraction: :func:`first_k_offenders` +
    :func:`gather_forensics` reduce the per-group violation masks ON
    DEVICE to the first-K offending group ids and gather ONLY those
    groups' rings across PCIe (a [W, M, K] transfer, never [W, M, C]);
    :func:`forensics_report` host-decodes them into per-round,
    per-member human-readable timelines for chaos_run.py's JSON.
  * :func:`to_chrome_trace` — Chrome trace-event JSON (one track per
    member for ring timelines, one track per request for host Trace
    spans) loadable in Perfetto — the repo's first correlated
    device-round <-> host-request view.

All three PR-9 hardening lessons apply: init gives every leaf its OWN
buffer (the chaos programs donate the carry; XLA rejects one buffer at
two donated positions), only device-reduced narrow slices ever cross
PCIe, and decoded output is RFC-8259-clean JSON.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from etcd_tpu.models.state import NodeState
from etcd_tpu.types import (
    MSG_APP,
    MSG_APP_RESP,
    MSG_HEARTBEAT,
    MSG_HEARTBEAT_RESP,
    MSG_HUP,
    MSG_PRE_VOTE,
    MSG_PRE_VOTE_RESP,
    MSG_SNAP,
    MSG_SNAP_STATUS,
    MSG_TIMEOUT_NOW,
    MSG_TRANSFER_LEADER,
    MSG_VOTE,
    MSG_VOTE_RESP,
    ROLE_LEADER,
    Spec,
)

# ring window (rounds of history per group); a build-time knob like the
# telemetry bucket count — small enough that ring[W, M, C] stays a
# minor fraction of the log ([L, E, C] i32) at the bench geometries
DEFAULT_WINDOW = 32

# ---------------------------------------------------------------------------
# event-word bit layout (i32; bit 31 stays clear so words are always
# non-negative — decode_word never has to think about sign extension)
#
#   bits  0-1   role after the round (ROLE_* 0..3)
#   bit   2     role transition (post.role != pre.role)
#   bits  3-5   term delta this round, clamped to [0, 7]
#   bits  6-8   commit frontier delta, clamped to [0, 7]
#   bits  9-11  applied frontier delta, clamped to [0, 7]
#   bit   12    snapshot install (applied jump > Spec.A — the same sound
#               detector as telemetry/build_kv_round)
#   bit   13    conf-change apply (any applied config mask changed)
#   bit   14    crashed this round (chaos tier)
#   bit   15    restart completed this round (chaos tier)
#   bit   16    down this round (chaos tier)
#   bits 17-20  message classes SENT (bitmask: append/election/heartbeat/
#               other — see MSG_CLASSES)
#   bits 21-24  message classes RECEIVED (same bitmask)
#   bits 25-27  messages sent, clamped to [0, 7]
#   bits 28-30  messages received, clamped to [0, 7]
# ---------------------------------------------------------------------------

ROLE_NAMES = ("follower", "pre-candidate", "candidate", "leader")
MSG_CLASSES = ("append", "election", "heartbeat", "other")

# message-type -> class id (1-based; 0 = empty slot). Index by msg type.
_CLASS_APPEND = (MSG_APP, MSG_APP_RESP, MSG_SNAP, MSG_SNAP_STATUS)
_CLASS_ELECT = (MSG_VOTE, MSG_VOTE_RESP, MSG_PRE_VOTE, MSG_PRE_VOTE_RESP,
                MSG_TIMEOUT_NOW, MSG_TRANSFER_LEADER, MSG_HUP)
_CLASS_HEARTBEAT = (MSG_HEARTBEAT, MSG_HEARTBEAT_RESP)
_N_MSG_TYPES = 18


def _class_table() -> np.ndarray:
    t = np.zeros((_N_MSG_TYPES,), np.int32)
    t[list(_CLASS_APPEND)] = 1
    t[list(_CLASS_ELECT)] = 2
    t[list(_CLASS_HEARTBEAT)] = 3
    # every remaining nonzero type (prop, read-index, unreachable, ...)
    t[1:][t[1:] == 0] = 4
    return t


_CLASS_TABLE = _class_table()


class EventRing(struct.PyTreeNode):
    """Device-resident event ring. ``ring[W, M, C]`` i32 event words for
    the last W rounds (slot = round % W); ``round`` counts rounds
    observed. Both are read-only reductions of the round — the ring
    never feeds back into state."""

    round: jnp.ndarray  # i32 rounds observed
    ring: jnp.ndarray   # [W, M, C] i32 bit-packed event words


def init_blackbox(spec: Spec, state: NodeState,
                  window: int = DEFAULT_WINDOW) -> EventRing:
    """Ring attached to a live (unpacked) fleet. Every leaf gets its OWN
    freshly-computed buffer, never an alias of a state leaf: the chaos
    epoch programs donate the whole carry on accelerators and XLA
    rejects one buffer at two donated positions in a single Execute
    (the empty_crash_state hazard class; tests assert distinctness)."""
    if not 2 <= window <= 256:
        raise ValueError(f"blackbox window={window} outside [2, 256]")
    C = state.term.shape[-1]
    return EventRing(
        round=jnp.zeros((), jnp.int32),
        ring=jnp.zeros((window, spec.M, C), jnp.int32),
    )


def _msg_activity(spec: Spec, msg) -> tuple:
    """Per-member message activity from a wire pytree in either storage
    form: (sent_count, recv_count, sent_cls, recv_cls), each [M, C]
    (class leaves [4, M, C] bool). Senders are attributed by the ``frm``
    field (exact in the flat form where axis 0 is the sender, and in
    the compacted carry form where it is not); receivers by the flat
    middle-axis layout slot*M + to shared by both forms."""
    M = spec.M
    t = msg.type.astype(jnp.int32)
    live = t != 0
    cls = jnp.asarray(_CLASS_TABLE)[jnp.clip(t, 0, _N_MSG_TYPES - 1)]
    mem = jnp.arange(M, dtype=jnp.int32)
    frm = msg.frm.astype(jnp.int32)
    to_ids = jnp.arange(t.shape[1], dtype=jnp.int32) % M         # [S]
    # [A, S, M, C] bool temporaries — A*S is tens of slots at the chaos
    # specs, so these stay small next to the log
    is_sender = live[:, :, None, :] & (frm[:, :, None, :] == mem[None, None, :, None])
    is_recv = live[:, :, None, :] & (to_ids[None, :, None, None] == mem[None, None, :, None])
    sent = is_sender.sum(axis=(0, 1)).astype(jnp.int32)          # [M, C]
    recv = is_recv.sum(axis=(0, 1)).astype(jnp.int32)
    sent_cls = jnp.stack([
        (is_sender & (cls[:, :, None, :] == g)).any(axis=(0, 1))
        for g in range(1, 5)])                                   # [4, M, C]
    recv_cls = jnp.stack([
        (is_recv & (cls[:, :, None, :] == g)).any(axis=(0, 1))
        for g in range(1, 5)])
    return sent, recv, sent_cls, recv_cls


def _event_word(spec: Spec, pre: NodeState, post: NodeState, inbox, outbox,
                crashed, restarted, down) -> jnp.ndarray:
    """One round's [M, C] bit-packed event words (layout above)."""
    i32 = jnp.int32
    w = post.role.astype(i32) & 0x3
    w = w | ((post.role != pre.role).astype(i32) << 2)
    w = w | (jnp.clip(post.term - pre.term, 0, 7).astype(i32) << 3)
    w = w | (jnp.clip(post.commit - pre.commit, 0, 7).astype(i32) << 6)
    dap = post.applied - pre.applied
    w = w | (jnp.clip(dap, 0, 7).astype(i32) << 9)
    w = w | ((dap > spec.A).astype(i32) << 12)
    cc = ((pre.voters != post.voters)
          | (pre.voters_out != post.voters_out)
          | (pre.learners != post.learners)
          | (pre.learners_next != post.learners_next)).any(axis=1)
    w = w | (cc.astype(i32) << 13)
    if crashed is not None:
        w = w | (crashed.astype(i32) << 14)
    if restarted is not None:
        w = w | (restarted.astype(i32) << 15)
    if down is not None:
        w = w | (down.astype(i32) << 16)
    if outbox is not None:
        sent, _, sent_cls, _ = _msg_activity(spec, outbox)
        bits = jnp.zeros_like(sent)
        for g in range(4):
            bits = bits | (sent_cls[g].astype(i32) << (17 + g))
        w = w | bits | (jnp.clip(sent, 0, 7).astype(i32) << 25)
    if inbox is not None:
        _, recv, _, recv_cls = _msg_activity(spec, inbox)
        bits = jnp.zeros_like(recv)
        for g in range(4):
            bits = bits | (recv_cls[g].astype(i32) << (21 + g))
        w = w | bits | (jnp.clip(recv, 0, 7).astype(i32) << 28)
    return w


def blackbox_update(spec: Spec, bb: EventRing, pre: NodeState,
                    post: NodeState, inbox=None, outbox=None, crashed=None,
                    restarted=None, down=None, write_mask=None) -> EventRing:
    """One round's ring pass: pure reductions over the (unpacked)
    pre/post states and the consumed (``inbox``) / emitted (``outbox``)
    wire — reads only, so fusing it into a round program cannot perturb
    the state or wire trajectory.

    ``crashed``/``restarted``/``down`` ([M, C] bool or None) come from
    the chaos tier's crash bookkeeping; None compiles those flag lanes
    out. ``write_mask`` ([C] bool or None) gates which groups still
    record: the chaos tier freezes a group's ring at its first
    violation (recording stops at the crash, aviation-style), so the
    preserved window is the W rounds UP TO the violation rather than
    the end of the run."""
    W = bb.ring.shape[0]
    word = _event_word(spec, pre, post, inbox, outbox, crashed, restarted,
                       down)
    sel = jnp.arange(W, dtype=jnp.int32)[:, None, None] == bb.round % W
    if write_mask is not None:
        sel = sel & write_mask[None, None, :]
    return EventRing(round=bb.round + 1,
                     ring=jnp.where(sel, word[None], bb.ring))


# ---------------------------------------------------------------------------
# device-side on-violation reduction
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnums=(1,))
def first_k_offenders(mask: jnp.ndarray, k: int) -> jnp.ndarray:
    """First-K set group ids of a [C] bool mask, ON DEVICE: i32[k] ids
    in ascending order, padded with the sentinel C when fewer than k
    groups are set. The sort runs over one [C] i32 lane — never a
    fleet-scaled transfer."""
    C = mask.shape[0]
    idx = jnp.where(mask, jnp.arange(C, dtype=jnp.int32), C)
    return jnp.sort(idx)[:k]


# lint: allow-def(host-sync) -- the documented narrow transfer: first-K offender lanes only
def gather_forensics(ring: EventRing, viol_groups: jnp.ndarray,
                     viol_round: jnp.ndarray, k: int) -> dict:
    """Reduce + gather on device, then ONE narrow host transfer: the
    first-K offending group ids and ONLY those groups' ring lanes
    ([W, M, k] — the full [W, M, C] ring never crosses PCIe). Returns
    numpy arrays keyed ids/rings/bits/viol_round/total/round; callers
    (and the device-reduction acceptance test) can check rings.shape[-1]
    == k directly."""
    C = viol_groups.shape[0]
    ids = first_k_offenders(viol_groups != 0, k)
    safe = jnp.minimum(ids, C - 1)  # sentinel lanes gather a dummy group
    return jax.device_get({
        "ids": ids,
        "rings": ring.ring[:, :, safe],
        "bits": viol_groups[safe],
        "viol_round": viol_round[safe],
        "total": (viol_groups != 0).sum().astype(jnp.int32),
        "round": ring.round,
    })


# ---------------------------------------------------------------------------
# host-side decode
# ---------------------------------------------------------------------------


def decode_word(w: int) -> dict:
    """One event word -> a plain field dict (layout above)."""
    w = int(w)
    sent_cls = [MSG_CLASSES[g] for g in range(4) if (w >> (17 + g)) & 1]
    recv_cls = [MSG_CLASSES[g] for g in range(4) if (w >> (21 + g)) & 1]
    return {
        "role": ROLE_NAMES[w & 0x3],
        "role_change": bool((w >> 2) & 1),
        "term_delta": (w >> 3) & 0x7,
        "commit_delta": (w >> 6) & 0x7,
        "applied_delta": (w >> 9) & 0x7,
        "snapshot_install": bool((w >> 12) & 1),
        "conf_change": bool((w >> 13) & 1),
        "crashed": bool((w >> 14) & 1),
        "restarted": bool((w >> 15) & 1),
        "down": bool((w >> 16) & 1),
        "sent": sent_cls,
        "recv": recv_cls,
        "sent_count": (w >> 25) & 0x7,
        "recv_count": (w >> 28) & 0x7,
    }


def word_events(d: dict) -> list:
    """Human-readable event strings for one decoded word (the forensics
    timeline's per-member lines)."""
    ev = []
    if d["crashed"]:
        ev.append("crash")
    if d["restarted"]:
        ev.append("restart")
    if d["down"]:
        ev.append("down")
    if d["role_change"]:
        ev.append("became-leader" if d["role"] == ROLE_NAMES[ROLE_LEADER]
                  else f"became-{d['role']}")
    if d["term_delta"]:
        ev.append(f"term+{d['term_delta']}")
    if d["snapshot_install"]:
        ev.append("snap-install")
    elif d["applied_delta"]:
        ev.append(f"applied+{d['applied_delta']}")
    if d["commit_delta"]:
        ev.append(f"commit+{d['commit_delta']}")
    if d["conf_change"]:
        ev.append("conf-change")
    if d["sent"]:
        ev.append("sent:" + "|".join(d["sent"]))
    if d["recv"]:
        ev.append("recv:" + "|".join(d["recv"]))
    return ev


def ring_timeline(ring_wm: np.ndarray, end_round: int) -> list:
    """Decode one group's ring lanes ([W, M] i32) into per-round rows.
    ``end_round`` is the LAST round the ring recorded for this group
    (the violation round for a frozen group, rounds_observed - 1
    otherwise); the ring covers rounds [end_round - W + 1, end_round]
    clipped at 0."""
    W, M = ring_wm.shape
    rows = []
    for r in range(max(0, end_round - W + 1), end_round + 1):
        members = []
        for m in range(M):
            d = decode_word(ring_wm[r % W, m])
            members.append({"member": m, "role": d["role"],
                            "word": int(ring_wm[r % W, m]),
                            "events": word_events(d)})
        rows.append({"round": r, "members": members})
    return rows


# bit order matches harness.chaos.VIOLATION_KEYS (kept literal here to
# avoid a models -> harness import cycle; chaos.py asserts the order)
VIOLATION_BIT_NAMES = (
    "multi_leader", "hash_mismatch", "commit_regress",
    "lost_commit", "log_divergence", "term_regress",
)


def violation_names(bits: int) -> list:
    return [n for i, n in enumerate(VIOLATION_BIT_NAMES)
            if (int(bits) >> i) & 1]


# lint: allow-def(host-sync) -- host-side post-mortem decode of the gathered lanes
def forensics_report(ring: EventRing, viol_groups: jnp.ndarray,
                     viol_round: jnp.ndarray, k: int = 4) -> dict:
    """The chaos post-mortem: device-reduce to the first-K offending
    groups, gather only their rings, and host-decode each into a
    per-round, per-member human-readable timeline. A persist-nothing
    run's report pinpoints the lost-commit round
    (first_violation_round) with the crash/role/commit events of the
    rounds leading up to it."""
    g = gather_forensics(ring, viol_groups, viol_round, k)
    W = ring.ring.shape[0]
    C = viol_groups.shape[0]
    rounds = int(g["round"])
    captured = []
    for i, gid in enumerate(np.asarray(g["ids"])):
        if int(gid) >= C:
            break  # sentinel: fewer than k offenders
        vr = int(g["viol_round"][i])
        end = vr if vr >= 0 else rounds - 1
        captured.append({
            "group": int(gid),
            "violations": violation_names(int(g["bits"][i])),
            "first_violation_round": vr,
            "timeline": ring_timeline(np.asarray(g["rings"][:, :, i]), end),
        })
    return {
        "window": W,
        "rounds_observed": rounds,
        "groups_violating": int(g["total"]),
        "captured": captured,
    }


# lint: allow-def(host-sync) -- host-side serving-path decode; gathers only requested lanes
def ring_capture(ring: EventRing, group_ids) -> list:
    """Decode live (non-violation) ring lanes for the given groups — the
    serving path's view for to_chrome_trace. Gathers only the requested
    groups' lanes ([W, M, len(ids)]) across PCIe."""
    ids = jnp.asarray(list(group_ids), jnp.int32)
    g = jax.device_get({"rings": ring.ring[:, :, ids], "round": ring.round})
    end = int(g["round"]) - 1
    return [{"group": int(gid),
             "timeline": ring_timeline(np.asarray(g["rings"][:, :, i]), end)}
            for i, gid in enumerate(group_ids)]


# ---------------------------------------------------------------------------
# Chrome trace-event export (Perfetto / chrome://tracing)
# ---------------------------------------------------------------------------

# host request spans land on their own synthetic process id, far from
# any plausible group id
HOST_PID = 1 << 20


def to_chrome_trace(captures=None, spans=None, round_us: int = 1000) -> dict:
    """Chrome trace-event JSON (the {"traceEvents": [...]} form Perfetto
    and chrome://tracing load): one track per MEMBER for device ring
    timelines (pid = group id, tid = member id; each round is a
    ``round_us``-microsecond complete event named by its decoded
    events) and one track per REQUEST for host Trace spans (pid =
    HOST_PID, tid = request index; the span plus one child slice per
    trace step). ``captures`` is forensics_report()["captured"] or
    ring_capture() output; ``spans`` is a list of Trace.to_span()
    dicts. Dump with json.dump and load the file at ui.perfetto.dev."""
    events = []
    for cap in captures or []:
        g = int(cap["group"])
        events.append({"ph": "M", "name": "process_name", "pid": g,
                       "tid": 0, "args": {"name": f"raft group {g} "
                                                  "(device rounds)"}})
        seen_members = set()
        for row in cap["timeline"]:
            ts = row["round"] * round_us
            for ent in row["members"]:
                m = int(ent["member"])
                if m not in seen_members:
                    seen_members.add(m)
                    events.append({"ph": "M", "name": "thread_name",
                                   "pid": g, "tid": m,
                                   "args": {"name": f"member {m}"}})
                name = ", ".join(ent["events"]) or ent["role"]
                events.append({
                    "ph": "X", "cat": "device", "name": name,
                    "pid": g, "tid": m, "ts": ts, "dur": round_us,
                    "args": {"round": row["round"], "role": ent["role"],
                             "word": ent["word"]},
                })
    if spans:
        events.append({"ph": "M", "name": "process_name", "pid": HOST_PID,
                       "tid": 0, "args": {"name": "host requests"}})
        t0 = min(s["start"] for s in spans)
        for i, s in enumerate(spans):
            events.append({"ph": "M", "name": "thread_name",
                           "pid": HOST_PID, "tid": i,
                           "args": {"name": f"req {i}: {s['op']}"}})
            base = (s["start"] - t0) * 1e6
            events.append({
                "ph": "X", "cat": "host", "name": s["op"],
                "pid": HOST_PID, "tid": i, "ts": base,
                "dur": s["dur"] * 1e6, "args": dict(s.get("fields", {})),
            })
            prev = 0.0
            for st in s.get("steps", []):
                events.append({
                    "ph": "X", "cat": "host", "name": st["msg"],
                    "pid": HOST_PID, "tid": i, "ts": base + prev * 1e6,
                    "dur": max(st["ts"] - prev, 0.0) * 1e6,
                    "args": dict(st.get("fields", {})),
                })
                prev = st["ts"]
    return {"traceEvents": events, "displayTimeUnit": "ms"}
