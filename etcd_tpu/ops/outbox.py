"""Per-node outbox: K message slots per destination with overflow-drop.

The reference accumulates outbound messages in ``r.msgs`` (raft/raft.go:264,
appended by send() at raft.go:386-419) and the transport may drop messages
("Send MUST NOT block / drop is OK", server/etcdserver/raft.go:107-110;
rafttest/network.go:106-108). Here the outbox is a dense ``[M, K]`` plane of
Msg slots plus a per-destination fill counter; emitting past K drops the
message, which is legal by the same contract.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from etcd_tpu.types import Msg, NONE_ID, Spec, empty_msg


class Outbox(struct.PyTreeNode):
    msgs: Msg              # leaves [M, K, ...]
    counts: jnp.ndarray    # i32[M]


def empty_outbox(spec: Spec) -> Outbox:
    m = empty_msg(spec)
    msgs = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (spec.M, spec.K) + x.shape), m
    )
    return Outbox(msgs=msgs, counts=jnp.zeros((spec.M,), jnp.int32))


def make_msg(spec: Spec, **kw) -> Msg:
    """A scalar Msg with given fields, rest defaulted."""
    base = empty_msg(spec)
    conv = {}
    for k, v in kw.items():
        ref = getattr(base, k)
        conv[k] = jnp.asarray(v, ref.dtype)
    return base.replace(**conv)


def bcast(spec: Spec, m: Msg) -> Msg:
    """Broadcast a scalar Msg to per-destination leaves [M, ...]."""
    return jax.tree.map(lambda x: jnp.broadcast_to(x, (spec.M,) + x.shape), m)


def emit(spec: Spec, ob: Outbox, to_mask: jnp.ndarray, m: Msg) -> Outbox:
    """Write per-destination message m (leaves [M, ...]) into the next free
    slot for every destination in `to_mask`; silently drop on overflow."""
    slot_idx = ob.counts                       # [M]
    can = to_mask & (slot_idx < spec.K)        # [M]
    sel = can[:, None] & (
        jnp.arange(spec.K, dtype=jnp.int32)[None, :] == slot_idx[:, None]
    )  # [M, K]

    def upd(old, new):
        extra = old.ndim - 2
        s = sel.reshape(sel.shape + (1,) * extra)
        return jnp.where(s, new[:, None], old)

    msgs = jax.tree.map(upd, ob.msgs, m)
    return Outbox(msgs=msgs, counts=ob.counts + can.astype(jnp.int32))


def emit_one(
    spec: Spec, ob: Outbox, to: jnp.ndarray, m: Msg, enable: jnp.ndarray
) -> Outbox:
    """Emit a scalar Msg to a single destination id (gated by `enable`)."""
    to_mask = (jnp.arange(spec.M, dtype=jnp.int32) == to) & enable
    return emit(spec, ob, to_mask, bcast(spec, m))
