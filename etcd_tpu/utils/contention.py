"""Starvation / contention detector (pkg/contention/contention.go).

The reference arms one around the leader's heartbeat sends
(etcdserver/raft.go:133: max = 2 x heartbeat interval; raft.go:357 observes
per-follower and warns "leader failed to send out heartbeat on time") —
late heartbeats mean the raft loop is starved by slow disk or an
overloaded scheduler. The TPU runtime's equivalent hot loop is the host
tick/pump cadence driving the device fleet: embed's ticker observes here
every tick, a late tick increments the counters surfaced in /metrics and
warns through the wired logger.
"""
from __future__ import annotations

import threading
import time


class TimeoutDetector:
    """Observes events that should recur within ``max_duration`` seconds;
    reports (on_time, exceeded_by_seconds) per observation."""

    def __init__(self, max_duration: float, clock=None):
        self.max_duration = max_duration
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._records: dict = {}
        # rollup for metrics/tests (the prometheus counter analog)
        self.late_total = 0
        self.max_exceeded = 0.0

    def reset(self) -> None:
        """Forget history — e.g. after a leadership change, when lateness
        blame does not carry over (raft.go:189)."""
        with self._lock:
            self._records.clear()

    def observe(self, which=0) -> tuple[bool, float]:
        now = self._clock()
        with self._lock:
            prev = self._records.get(which)
            self._records[which] = now
            if prev is None:
                return True, 0.0
            exceeded = (now - prev) - self.max_duration
            if exceeded > 0:
                self.late_total += 1
                self.max_exceeded = max(self.max_exceeded, exceeded)
                return False, exceeded
            return True, 0.0
