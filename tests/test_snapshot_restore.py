"""etcdutl snapshot restore — disaster recovery from a saved snapshot
(etcdutl/etcdutl/snapshot_command.go:81 status, :122 restore): a data dir
rewritten offline from a snapshot file boots as a fresh cluster whose
applied state (KV revisions, lease, auth, alarms) matches the snapshot,
verified by hashKV equality, and which accepts new writes.
"""
import json
import os
import pickle

import pytest

from etcd_tpu import etcdutl
from etcd_tpu.server.kvserver import EtcdCluster


@pytest.fixture
def ec_with_data(tmp_path):
    ec = EtcdCluster(data_dir=str(tmp_path / "orig"))
    ec.ensure_leader()
    ec.put(b"k/1", b"v1")
    ec.put(b"k/2", b"v2")
    ec.put(b"k/1", b"v1b")      # a second revision of k/1
    ec.delete_range(b"k/2")     # and a tombstone
    ec.put(b"k/3", b"v3")
    ec.lease_grant(77, ttl=600)
    ec.put(b"k/leased", b"lv", lease=77)
    ec.stabilize()
    return ec


def _save(ec, path):
    """etcdctl snapshot save: write the pickled member snapshot the
    gateway streams (etcdctl.py `snapshot` / v3rpc maintenance_snapshot)."""
    with open(path, "wb") as f:
        pickle.dump(ec.member_snapshot(ec.ensure_leader()), f, protocol=4)


def test_snapshot_status(ec_with_data, tmp_path, capsys):
    snap_file = str(tmp_path / "snap.db")
    _save(ec_with_data, snap_file)
    assert etcdutl.main(["snapshot", "status", snap_file]) == 0
    st = json.loads(capsys.readouterr().out)
    assert st["applied_index"] == ec_with_data.members[0].applied_index
    assert st["revision"] == ec_with_data.members[0].store.kv.current_rev
    assert st["total_key_revisions"] == 6  # 5 puts + 1 tombstone


def test_snapshot_restore_round_trip(ec_with_data, tmp_path, capsys):
    """put -> snapshot save -> restore -> reboot -> range/hashKV match."""
    ec = ec_with_data
    snap_file = str(tmp_path / "snap.db")
    _save(ec, snap_file)
    want_hash = ec.hash_kv(ec.ensure_leader())
    want_rev = ec.members[0].store.kv.current_rev
    restored_dir = str(tmp_path / "restored")

    assert etcdutl.main([
        "snapshot", "restore", snap_file, "--data-dir", restored_dir,
        "--members", "3",
    ]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["consistent_index"] == ec.members[0].applied_index
    assert sorted(os.listdir(restored_dir)) == [
        "member0.db", "member1.db", "member2.db"
    ]

    ec2 = EtcdCluster.boot_from_disk(restored_dir)
    ec2.ensure_leader()
    # every member restored at the same applied index with equal KV hash
    for m in range(3):
        assert ec2.members[m].applied_index == ec.members[0].applied_index
        assert ec2.hash_kv(m) == want_hash
    ec2.corruption_check()
    # MVCC history fully preserved: live keys, tombstone, old revisions
    assert ec2.range(b"k/1")["kvs"][0].value == b"v1b"
    assert ec2.range(b"k/2")["count"] == 0
    assert ec2.range(b"k/3")["kvs"][0].value == b"v3"
    old = ec2.range(b"k/1", rev=want_rev - 4)  # before the k/1 overwrite
    assert old["kvs"][0].value == b"v1"
    # lease attachment survived
    assert 77 in ec2.leases()
    assert ec2.range(b"k/leased")["kvs"][0].lease == 77


def test_restored_cluster_accepts_new_writes(ec_with_data, tmp_path):
    ec = ec_with_data
    snap_file = str(tmp_path / "snap.db")
    _save(ec, snap_file)
    restored_dir = str(tmp_path / "restored")
    etcdutl.restore_snapshot(snap_file, restored_dir, members=3)

    ec2 = EtcdCluster.boot_from_disk(restored_dir)
    ec2.ensure_leader()
    base_index = ec2.members[0].applied_index
    ec2.put(b"new/after-restore", b"yes")
    ec2.stabilize()
    assert ec2.range(b"new/after-restore")["kvs"][0].value == b"yes"
    # consensus resumed past the synthetic snapshot index
    assert all(ms.applied_index > base_index for ms in ec2.members)
    ec2.corruption_check()
    # and the new state persists across a member restart from disk
    ec2.crash_member(1)
    ec2.restart_member_from_disk(1)
    ec2.stabilize()
    assert ec2.hash_kv(1) == ec2.hash_kv(0)


def test_restore_rejects_mixed_data_dir(ec_with_data, tmp_path):
    """boot_from_disk refuses a data dir whose members disagree on the
    restored index (a half-written restore must fail loudly)."""
    ec = ec_with_data
    snap_file = str(tmp_path / "snap.db")
    _save(ec, snap_file)
    d = str(tmp_path / "mixed")
    etcdutl.restore_snapshot(snap_file, d, members=3)

    # corrupt member 2: restore it from a doctored snapshot at another index
    doctored = pickle.load(open(snap_file, "rb"))
    doctored["applied_index"] += 5
    with open(snap_file, "wb") as f:
        pickle.dump(doctored, f, protocol=4)
    one = str(tmp_path / "one")
    etcdutl.restore_snapshot(snap_file, one, members=1)
    os.replace(os.path.join(one, "member0.db"), os.path.join(d, "member2.db"))

    from etcd_tpu.server.kvserver import ServerError

    with pytest.raises(ServerError):
        EtcdCluster.boot_from_disk(d)
