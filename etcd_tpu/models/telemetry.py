"""Device-resident fleet telemetry plane: per-group lanes + latency
histograms + Prometheus exposition.

The reference instruments everything with Prometheus histograms and
per-node Status (etcdserver/metrics.go, raft/status.go); FleetMetrics
(models/metrics.py) gave the fleet scalar counters and one lag
histogram, but no latency *distributions*, no per-group resolution and
no time dimension. This module adds the missing substrate:

  * :class:`FleetTelemetry` — a pytree riding BESIDE the fleet state
    through the traced round: per-group event lanes ``[C]`` (leader
    changes, snapshot installs, crash-heal rounds) and fused
    power-of-two-bucket latency histograms for propose→commit round
    latency, election duration (candidate→leader rounds) and post-crash
    heal time (restart→caught-up-to-commit-frontier).
  * :func:`telemetry_update` — ONE pure function of (pre, post) round
    states; every consumer (the metered round, the chaos epoch scan,
    the serving-layer Cluster) calls the same math, so the numbers mean
    the same thing everywhere. Telemetry only READS state — it never
    feeds back — so a telemetry-on round is bit-identical in state to
    the telemetry-off round (tests/test_telemetry.py proves it over the
    rich full-program scenario, including under the PR-8 diet).
  * host-side reporting: cumulative-bucket dicts, percentile extraction
    (p50/p99 for bench.py), per-epoch :func:`flight_record` snapshots
    (the chaos flight recorder's timeline rows), and Prometheus
    exposition-format render/parse for the ``/metrics`` endpoint.

Propose→commit latency is tracked with a small BIRTH RING ``[L, C]``
alongside the log cursor: the round each log index first appeared at
the group's append frontier. When the group commit frontier passes an
index, ``round - birth`` is bucketed. A suffix truncated and rewritten
by a new leader keeps the earlier birth (the sample then measures the
client-visible wait since the index first existed — conservative);
entries in flight when telemetry attaches sample from the attach round.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from etcd_tpu.models.state import NodeState
from etcd_tpu.types import (
    ROLE_CANDIDATE,
    ROLE_LEADER,
    ROLE_PRE_CANDIDATE,
    Spec,
)

# power-of-two histogram edges 1, 2, 4, ..., 2^(buckets-1) rounds; the
# final histogram slot is the +Inf bucket (= total sample count), the
# same cumulative Prometheus convention as metrics.LAG_BUCKETS
DEFAULT_BUCKETS = 8


def pow2_edges(buckets: int) -> tuple:
    return tuple(1 << i for i in range(buckets))


class FleetTelemetry(struct.PyTreeNode):
    """Device-resident telemetry carry. Counter lanes are i32 and reset
    per measurement window like FleetMetrics (telemetry_report raises on
    wrap). The ``birth_ring``/``prev_*``/``*_since`` leaves are tracking
    carries, not metrics — they exist so transitions can be detected
    with pure tensor math inside the traced round."""

    round: jnp.ndarray              # i32 rounds observed
    # per-group event lanes [C]
    leader_changes: jnp.ndarray     # rounds a new leader emerged
    snapshot_installs: jnp.ndarray  # MsgSnap installs (applied jump > A)
    heal_rounds: jnp.ndarray        # rounds with a member down/healing
    # latency histograms: [buckets+1] cumulative pow2 counts (+Inf last)
    commit_hist: jnp.ndarray        # propose→commit rounds
    commit_sum: jnp.ndarray         # i32 sum of samples (Prometheus _sum)
    elect_hist: jnp.ndarray         # candidate→leader rounds
    elect_sum: jnp.ndarray
    heal_hist: jnp.ndarray          # restart→caught-up rounds
    heal_sum: jnp.ndarray
    # tracking carries
    birth_ring: jnp.ndarray         # [L, C] round each index was appended
    prev_last: jnp.ndarray          # [C] group append frontier last round
    prev_commit: jnp.ndarray        # [C] running max group commit frontier
    cand_since: jnp.ndarray         # [M, C] round candidacy began (-1 none)
    heal_since: jnp.ndarray         # [M, C] round restart completed (-1)


def init_telemetry(spec: Spec, state: NodeState,
                   buckets: int = DEFAULT_BUCKETS) -> FleetTelemetry:
    """Telemetry attached to a live (unpacked) fleet. The prev_* carries
    seed from the current frontiers — entries already in flight sample
    their latency from the attach round (bounded by the pipeline depth).
    All leaves are freshly computed buffers, never aliases of state
    leaves (the empty_crash_state donation-alias hazard class)."""
    if not 2 <= buckets <= 16:
        raise ValueError(f"telemetry buckets={buckets} outside [2, 16]")
    C = state.term.shape[-1]

    # every leaf gets its OWN buffer: the chaos epoch programs donate
    # the whole carry on accelerators, and XLA rejects one buffer at
    # two donated positions in a single Execute — a shared zeros/scalar
    # temp across leaves would crash the first donated epoch call
    # (tests/test_telemetry.py asserts pairwise-distinct leaf buffers)
    def z():
        return jnp.zeros((), jnp.int32)

    def zc():
        return jnp.zeros((C,), jnp.int32)

    def zh():
        return jnp.zeros((buckets + 1,), jnp.int32)

    def neg():
        return jnp.full((spec.M, C), -1, jnp.int32)

    return FleetTelemetry(
        round=z(),
        leader_changes=zc(), snapshot_installs=zc(), heal_rounds=zc(),
        commit_hist=zh(), commit_sum=z(),
        elect_hist=zh(), elect_sum=z(),
        heal_hist=zh(), heal_sum=z(),
        birth_ring=jnp.zeros((spec.L, C), jnp.int32),
        prev_last=state.last_index.max(axis=0),
        prev_commit=state.commit.max(axis=0),
        cand_since=neg(), heal_since=neg(),
    )


def _hist_add(hist, total_sum, samples, mask):
    """Fused cumulative pow2-bucket update: count masked samples into
    hist (<= edge per bucket, +Inf last) and accumulate their sum."""
    nb = hist.shape[0] - 1
    edges = jnp.asarray(pow2_edges(nb), jnp.int32)
    axes = tuple(range(samples.ndim))
    cum = ((samples[..., None] <= edges) & mask[..., None]).sum(axes)
    cnt = mask.sum()
    hist = hist + jnp.concatenate(
        [cum, cnt[None]]).astype(hist.dtype)
    total_sum = total_sum + jnp.where(mask, samples, 0).sum().astype(
        total_sum.dtype)
    return hist, total_sum


def telemetry_update(spec: Spec, tele: FleetTelemetry, pre: NodeState,
                     post: NodeState, restarted=None,
                     down=None) -> FleetTelemetry:
    """One round's telemetry pass: pure reductions over the (unpacked)
    pre/post round states — reads only, so fusing it into a round
    program cannot perturb the state trajectory.

    ``restarted``/``down`` ([M, C] bool or None) come from the chaos
    tier's crash bookkeeping: nodes whose restart completed this round
    (starts the heal clock) and nodes currently down (counts toward the
    group's heal_rounds lane). None compiles the heal machinery down to
    the carry passthrough it is without crash faults.
    """
    r = tele.round
    L = spec.L
    dt = jnp.int32

    # -- propose→commit latency via the birth ring -----------------------
    li = post.last_index.max(axis=0)                      # [C]
    cm = post.commit.max(axis=0)                          # [C]
    slots = jnp.arange(L, dtype=dt)[:, None]              # [L, 1]
    # log index currently stored at each ring slot given frontier li
    # (same cursor arithmetic as engine.member_window_mask)
    ent_idx = li[None, :] - ((li[None, :] - 1 - slots) % L)
    born = (ent_idx > tele.prev_last[None, :]) & (ent_idx > 0)
    birth = jnp.where(born, r, tele.birth_ring)
    # prev_commit is a RUNNING MAX: a commit frontier legally regressing
    # across a persist-nothing crash must not re-sample its entries
    committed = (
        (ent_idx > tele.prev_commit[None, :])
        & (ent_idx <= cm[None, :]) & (ent_idx > 0)
    )
    commit_hist, commit_sum = _hist_add(
        tele.commit_hist, tele.commit_sum,
        jnp.maximum(r - birth, 0), committed)

    # -- election duration (candidate→leader rounds) ---------------------
    is_cand = (post.role == ROLE_PRE_CANDIDATE) | (
        post.role == ROLE_CANDIDATE)
    cand_since = jnp.where(is_cand & (tele.cand_since < 0), r,
                           tele.cand_since)
    new_lead = (post.role == ROLE_LEADER) & (pre.role != ROLE_LEADER)
    elect_hist, elect_sum = _hist_add(
        tele.elect_hist, tele.elect_sum,
        jnp.where(cand_since >= 0, r - cand_since, 0), new_lead)
    # leaving candidacy (won, or demoted back to follower) clears the clock
    cand_since = jnp.where(is_cand, cand_since, -1)
    leader_changes = tele.leader_changes + new_lead.any(axis=0).astype(dt)

    # -- snapshot installs: ring apply advances `applied` by at most
    # Spec.A per round, so a bigger jump can only be a MsgSnap install
    # (the same sound detector as engine.build_kv_round); crash rewinds
    # move applied DOWN and never count
    inst = (post.applied - pre.applied) > spec.A
    snapshot_installs = tele.snapshot_installs + inst.any(axis=0).astype(dt)

    # -- post-crash heal time (restart → caught up to the commit frontier)
    heal_since = tele.heal_since
    if restarted is not None:
        heal_since = jnp.where(restarted, r, heal_since)
    healed = (heal_since >= 0) & (post.commit >= cm[None, :])
    if down is not None:
        healed = healed & ~down
    heal_hist, heal_sum = _hist_add(
        tele.heal_hist, tele.heal_sum,
        jnp.maximum(r - heal_since, 0), healed)
    heal_since = jnp.where(healed, -1, heal_since)
    healing = heal_since >= 0
    if down is not None:
        healing = healing | down
    heal_rounds = tele.heal_rounds + healing.any(axis=0).astype(dt)

    return tele.replace(
        round=r + 1,
        leader_changes=leader_changes,
        snapshot_installs=snapshot_installs,
        heal_rounds=heal_rounds,
        commit_hist=commit_hist, commit_sum=commit_sum,
        elect_hist=elect_hist, elect_sum=elect_sum,
        heal_hist=heal_hist, heal_sum=heal_sum,
        birth_ring=birth,
        prev_last=li,
        prev_commit=jnp.maximum(tele.prev_commit, cm),
        cand_since=cand_since, heal_since=heal_since,
    )


# ---------------------------------------------------------------------------
# host-side reporting
# ---------------------------------------------------------------------------


# lint: allow-def(host-sync) -- host-side report path; one narrow device_get per report window
def hist_percentile(hist, q: float):
    """Percentile from a cumulative pow2 histogram: the smallest bucket
    upper bound covering fraction q of the samples (Prometheus
    histogram_quantile semantics on our integer buckets). None with no
    samples; the +Inf bucket answers float('inf')."""
    h = np.asarray(hist)
    total = int(h[-1])
    if total == 0:
        return None
    target = q * total
    for i in range(len(h) - 1):
        if int(h[i]) >= target:
            return 1 << i
    return float("inf")


def _json_pctl(p):
    # a percentile past the top finite edge is the string "inf", never
    # float('inf'): json.dumps would emit the bare token Infinity,
    # which strict JSON parsers (jq, JSON.parse) reject — the evidence
    # files must stay RFC-8259 clean
    return "inf" if p == float("inf") else p


# lint: allow-def(host-sync) -- host-side report path; one narrow device_get per report window
def _hist_block(hist, total_sum) -> dict:
    h = np.asarray(hist)
    nb = len(h) - 1
    return {
        "hist": {**{f"le_{e}": int(c)
                    for e, c in zip(pow2_edges(nb), h[:-1])},
                 "inf": int(h[-1])},
        "count": int(h[-1]),
        "sum": int(total_sum),
        "p50": _json_pctl(hist_percentile(h, 0.5)),
        "p99": _json_pctl(hist_percentile(h, 0.99)),
    }


# lint: allow-def(host-sync) -- host-side report path; one narrow device_get per report window
def telemetry_report(tele: FleetTelemetry, groups: int | None = None) -> dict:
    """One host transfer -> plain-dict report. ``groups`` restricts the
    per-group lanes to the first N (the harness Cluster's canonical-lane
    padding must not leak idle lanes into lane aggregates)."""
    t = jax.device_get(tele)
    sl = slice(None) if groups is None else slice(0, groups)
    lanes = {
        "leader_changes": np.asarray(t.leader_changes)[sl],
        "snapshot_installs": np.asarray(t.snapshot_installs)[sl],
        "heal_rounds": np.asarray(t.heal_rounds)[sl],
    }
    out = {"rounds": int(t.round)}
    for name, v in lanes.items():
        out[f"{name}_total"] = int(v.sum())
        out[f"{name}_max_group"] = int(v.max()) if v.size else 0
    out["commit_latency_rounds"] = _hist_block(t.commit_hist, t.commit_sum)
    out["election_duration_rounds"] = _hist_block(t.elect_hist, t.elect_sum)
    out["heal_latency_rounds"] = _hist_block(t.heal_hist, t.heal_sum)
    # per-lane sign check: numpy sums int32 lanes in int64, so one
    # wrapped (negative) lane can hide behind other lanes' totals
    wrapped = any(bool((v < 0).any()) for v in lanes.values())
    for hist, s in ((t.commit_hist, t.commit_sum),
                    (t.elect_hist, t.elect_sum),
                    (t.heal_hist, t.heal_sum)):
        wrapped |= int(np.asarray(hist)[-1]) < 0 or int(np.asarray(s)) < 0
    if wrapped:
        raise OverflowError(
            "FleetTelemetry counter wrapped (i32); shorten the window or "
            "re-init telemetry per report window")
    return out


# lint: allow-def(host-sync) -- host-side flight-recorder row; transfers only the reduced scalars/histograms
def flight_record(tele: FleetTelemetry, viol=None, crash_metrics=None,
                  kind: str = "") -> dict:
    """One timeline row of the chaos flight recorder: a compact
    host-side snapshot of the cumulative telemetry + violation +
    crash counters at an epoch boundary. All counters are cumulative,
    so consecutive rows are monotone non-decreasing per field — the
    property the smoke tier asserts."""
    # narrow transfer: ONLY the histograms/scalars the row needs, with
    # the [C] lanes reduced on device — never the [L, C] birth ring or
    # the [M, C] clocks (at C=1M the ring alone is tens of MB; hauling
    # it to host twice per fault/heal cycle would dwarf the row)
    t = jax.device_get({
        "round": tele.round,
        "commit_hist": tele.commit_hist, "commit_sum": tele.commit_sum,
        "elect_hist": tele.elect_hist, "elect_sum": tele.elect_sum,
        "heal_hist": tele.heal_hist, "heal_sum": tele.heal_sum,
        "leader_changes": tele.leader_changes.sum(),
        "snapshot_installs": tele.snapshot_installs.sum(),
        "heal_rounds": tele.heal_rounds.sum(),
    })
    rec = {
        "kind": kind,
        "round": int(t["round"]),
        "commit_hist": [int(v) for v in np.asarray(t["commit_hist"])],
        "commit_sum": int(t["commit_sum"]),
        "elect_hist": [int(v) for v in np.asarray(t["elect_hist"])],
        "elect_sum": int(t["elect_sum"]),
        "heal_hist": [int(v) for v in np.asarray(t["heal_hist"])],
        "heal_sum": int(t["heal_sum"]),
        "leader_changes": int(t["leader_changes"]),
        "snapshot_installs": int(t["snapshot_installs"]),
        "heal_rounds": int(t["heal_rounds"]),
    }
    # an i32 wrap (very long window at very large C) shows up as a
    # negative counter; flag the row instead of silently breaking the
    # monotone-timeline property downstream consumers rely on
    rec["wrapped"] = (
        any(v < 0 for hk in ("commit_hist", "elect_hist", "heal_hist")
            for v in rec[hk])
        or any(rec[k] < 0 for k in ("commit_sum", "elect_sum", "heal_sum",
                                    "leader_changes", "snapshot_installs",
                                    "heal_rounds")))
    if viol is not None:
        v = jax.device_get(viol)
        rec["violations"] = {
            k: int(getattr(v, k)) for k in type(v).__dataclass_fields__
        }
    if crash_metrics is not None:
        m = jax.device_get(crash_metrics)
        for k in ("crashes_injected", "entries_lost_fsync",
                  "restarts_completed", "conf_changes_applied"):
            rec[k] = int(getattr(m, k))
    return rec


# ---------------------------------------------------------------------------
# Prometheus exposition format (the /metrics wire form)
# ---------------------------------------------------------------------------

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _fmt_value(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        if v == float("inf"):
            return "+Inf"
        return repr(v)
    return str(v)


def prometheus_render(families) -> str:
    """Render metric families to exposition text. ``families`` is a list
    of (name, mtype, help, samples); each sample is (suffix, labels,
    value) — suffix "" for plain counters/gauges, "_bucket"/"_sum"/
    "_count" for histogram series, labels a (possibly empty) dict."""
    lines = []
    for name, mtype, help_text, samples in families:
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")
        for suffix, labels, value in samples:
            lab = ""
            if labels:
                inner = ",".join(
                    f'{k}="{v}"' for k, v in labels.items())
                lab = "{" + inner + "}"
            lines.append(f"{name}{suffix}{lab} {_fmt_value(value)}")
    return "\n".join(lines) + "\n"


def histogram_samples(edges, cum_counts, count: int, total_sum) -> list:
    """The _bucket/_sum/_count triplet for one histogram family from
    cumulative bucket counts (+Inf implied by ``count``)."""
    out = [("_bucket", {"le": str(e)}, int(c))
           for e, c in zip(edges, cum_counts)]
    out.append(("_bucket", {"le": "+Inf"}, int(count)))
    out.append(("_sum", {}, total_sum))
    out.append(("_count", {}, int(count)))
    return out


def prometheus_parse(text: str) -> dict:
    """Parse exposition text back into families, VALIDATING conformance:
    every sample must belong to a # TYPE-declared family (histogram
    series match via their _bucket/_sum/_count suffixes), histogram
    buckets must be cumulative non-decreasing and end in an +Inf bucket
    equal to _count. Returns {family: {"type", "help", "samples":
    {(series_name, ((label, value), ...)): float}}} — the round-trip
    test re-renders and compares."""
    import re

    fams: dict = {}
    sample_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(\S+)$")
    for ln, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            fams.setdefault(name, {"samples": {}})["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, mtype = rest.partition(" ")
            if mtype not in ("counter", "gauge", "histogram", "summary",
                             "untyped"):
                raise ValueError(f"line {ln}: unknown metric type {mtype!r}")
            fams.setdefault(name, {"samples": {}})["type"] = mtype
            continue
        if line.startswith("#"):
            continue  # comment
        m = sample_re.match(line)
        if m is None:
            raise ValueError(f"line {ln}: unparseable sample {line!r}")
        sname, _, rawlab, rawval = m.groups()
        labels = {}
        if rawlab:
            for part in re.findall(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"',
                                   rawlab):
                labels[part[0]] = part[1]
        value = float(rawval.replace("+Inf", "inf"))
        fam = sname
        if fam not in fams:
            for suf in ("_bucket", "_sum", "_count"):
                if sname.endswith(suf) and sname[: -len(suf)] in fams:
                    fam = sname[: -len(suf)]
                    break
        if fam not in fams or "type" not in fams[fam]:
            raise ValueError(
                f"line {ln}: sample {sname!r} has no # TYPE declaration")
        key = (sname, tuple(sorted(labels.items())))
        fams[fam]["samples"][key] = value
    # histogram conformance: buckets cumulative, +Inf present == _count
    for name, fam in fams.items():
        if fam.get("type") != "histogram":
            continue
        raw_buckets = [(dict(k[1]).get("le"), v)
                       for k, v in fam["samples"].items()
                       if k[0] == name + "_bucket"]
        if any(le is None for le, _ in raw_buckets):
            raise ValueError(f"histogram {name} has a _bucket sample "
                             "without an le label")
        buckets = sorted(
            raw_buckets,
            key=lambda kv: float(kv[0].replace("+Inf", "inf")))
        if not buckets or buckets[-1][0] != "+Inf":
            raise ValueError(f"histogram {name} missing +Inf bucket")
        counts = [v for _, v in buckets]
        if any(a > b for a, b in zip(counts, counts[1:])):
            raise ValueError(f"histogram {name} buckets not cumulative")
        cnt = fam["samples"].get((name + "_count", ()))
        if cnt is None or cnt != buckets[-1][1]:
            raise ValueError(f"histogram {name} +Inf bucket != _count")
        if (name + "_sum", ()) not in fam["samples"]:
            raise ValueError(f"histogram {name} missing _sum")
    return fams


def server_metric_families(summary: dict, telemetry: dict | None = None,
                           contention=None, slow: dict | None = None) -> list:
    """The /metrics endpoint's family list: etcd-reference metric names
    over the fleet summary (models/metrics.py fleet_summary), the
    telemetry report's latency histograms when the serving cluster
    carries a telemetry plane, and the legacy etcd_tpu_* gauges the
    earlier evidence runs scraped. ``slow`` carries the kvserver's
    slow-request counters ({"slow_apply_total", "slow_read_indexes_
    total"}) — the reference's applyTook/slowReadIndex signals."""
    g = "gauge"

    def plain(v):
        return [("", {}, v)]

    fams = [
        ("etcd_server_has_leader", g,
         "Whether or not a leader exists (1 / 0).",
         plain(int(summary["groups_with_leader"] == summary["groups"]))),
        ("etcd_server_proposals_committed_total", g,
         "The total number of consensus proposals committed.",
         plain(summary["commit_max"])),
        ("etcd_server_proposals_applied_total", g,
         "The total number of consensus proposals applied.",
         plain(summary.get("applied_max", summary["commit_max"]))),
        ("etcd_server_proposals_pending", g,
         "The current number of pending proposals to commit.",
         plain(summary.get("lag_sum", 0))),
        ("etcd_server_leader_changes_seen_total", "counter",
         "The number of leader changes seen.",
         plain(telemetry["leader_changes_total"] if telemetry else 0)),
        # legacy gauges (kept verbatim: earlier scrapes + tests match
        # on these exact sample lines)
        ("etcd_tpu_groups", g, "Raft groups in the fleet.",
         plain(summary["groups"])),
        ("etcd_tpu_groups_with_leader", g, "Groups with >= 1 leader.",
         plain(summary["groups_with_leader"])),
        ("etcd_tpu_commit_max", g, "Max commit index across the fleet.",
         plain(summary["commit_max"])),
        ("etcd_tpu_commit_apply_lag_max", g,
         "Max commit-apply lag (entries).",
         plain(summary["commit_apply_lag_max"])),
        ("etcd_tpu_term_max", g, "Max term across the fleet.",
         plain(summary["term_max"])),
    ]
    lag_hist = summary.get("commit_apply_lag_hist")
    if lag_hist is not None:
        edges = [k[3:] for k in lag_hist if k.startswith("le_")]
        cum = [lag_hist[f"le_{e}"] for e in edges]
        fams.append((
            "etcd_tpu_commit_apply_lag_entries", "histogram",
            "Commit-apply lag across fleet nodes at scrape time "
            "(entries).",
            histogram_samples(edges, cum, lag_hist["inf"],
                              summary.get("lag_sum", 0)),
        ))
    if telemetry is not None:
        for key, mname, help_text in (
            ("commit_latency_rounds", "etcd_tpu_commit_latency_rounds",
             "Propose-to-commit latency (lockstep rounds)."),
            ("election_duration_rounds",
             "etcd_tpu_election_duration_rounds",
             "Candidate-to-leader election duration (lockstep rounds)."),
            ("heal_latency_rounds", "etcd_tpu_heal_latency_rounds",
             "Crash-restart to caught-up heal time (lockstep rounds)."),
        ):
            blk = telemetry[key]
            edges = [k[3:] for k in blk["hist"] if k.startswith("le_")]
            cum = [blk["hist"][f"le_{e}"] for e in edges]
            fams.append((mname, "histogram", help_text,
                         histogram_samples(edges, cum, blk["count"],
                                           blk["sum"])))
        fams.append((
            "etcd_tpu_snapshot_installs_total", "counter",
            "Snapshot installs observed (applied-jump detector).",
            plain(telemetry["snapshot_installs_total"])))
    if slow is not None:
        fams.append((
            "etcd_server_slow_apply_total", "counter",
            "The total number of slow apply requests "
            "(likely overloaded from slow disk).",
            plain(int(slow.get("slow_apply_total", 0)))))
        fams.append((
            "etcd_server_slow_read_indexes_total", "counter",
            "The total number of pending read indexes not in sync with "
            "leader's or timed out read index requests.",
            plain(int(slow.get("slow_read_indexes_total", 0)))))
    if contention is not None:
        fams.append((
            "etcd_tpu_ticker_late_total", "counter",
            "Ticks later than the contention threshold.",
            plain(contention.late_total)))
        fams.append((
            "etcd_tpu_ticker_late_max_seconds", g,
            "Worst observed tick lateness.",
            plain(float(f"{contention.max_exceeded:.6f}"))))
    return fams
