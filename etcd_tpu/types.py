"""Core wire/state types for the TPU-native batched Raft engine.

These mirror the *contracts* of the reference's ``raft/raftpb/raft.proto``
(message types at raft.proto:46-66, Entry/HardState at raft.proto:69-113)
but are laid out as dense, fixed-width integer fields so that a message is
a struct-of-arrays slot in a ``[clusters, members, members, K]`` tensor
rather than a protobuf on a wire.

Conventions (deliberately different from the Go reference where that makes
the tensor program better):
  * member ids are 0-based (0..M-1); "None" (no leader / no vote) is -1,
    not 0, so ids can index arrays directly.
  * terms/indexes are int32 (simulation-scale; the reference uses uint64).
  * member *sets* (ConfState voter/learner sets, raft.proto:115-130) are
    packed int32 bitmasks in messages and bool[M] masks in node state.
"""
from __future__ import annotations

import dataclasses
import functools

import jax.numpy as jnp
from flax import struct

# ---------------------------------------------------------------------------
# Scalar constants
# ---------------------------------------------------------------------------

NONE_ID = -1  # reference: None uint64 = 0 (raft/raft.go:35); we use -1
INT32_MAX = jnp.iinfo(jnp.int32).max  # stands in for math.MaxUint64 sentinels

# Roles (reference StateType, raft/raft.go:39-45)
ROLE_FOLLOWER = 0
ROLE_PRE_CANDIDATE = 1
ROLE_CANDIDATE = 2
ROLE_LEADER = 3

# Message types (reference raft/raftpb/raft.proto:46-66). Type 0 is reserved
# for "empty slot" so a zeroed message tensor means "no message".
MSG_NONE = 0
MSG_APP = 1
MSG_APP_RESP = 2
MSG_VOTE = 3
MSG_VOTE_RESP = 4
MSG_SNAP = 5
MSG_HEARTBEAT = 6
MSG_HEARTBEAT_RESP = 7
MSG_PRE_VOTE = 8
MSG_PRE_VOTE_RESP = 9
MSG_TRANSFER_LEADER = 10
MSG_TIMEOUT_NOW = 11
MSG_READ_INDEX = 12
MSG_READ_INDEX_RESP = 13
MSG_PROP = 14
MSG_UNREACHABLE = 15
MSG_SNAP_STATUS = 16
MSG_HUP = 17  # local campaign trigger; Msg.context selects the campaign kind
NUM_MSG_TYPES = 18

# Entry types (raft.proto:69-74)
ENTRY_NORMAL = 0
ENTRY_CONF_CHANGE = 1  # the device models the V2-equivalent, packed in data
# host-side raftpb surface (etcd_tpu/raftpb.py) distinguishes the wire
# entry types the way MarshalConfChange does (raftpb/confchange.go:34-47)
ENTRY_CONF_CHANGE_V2 = 2

# Vote results (reference quorum/quorum.go:50-58)
VOTE_PENDING = 0
VOTE_WON = 1
VOTE_LOST = 2

# Progress states (reference tracker/state.go:20-34)
PR_PROBE = 0
PR_REPLICATE = 1
PR_SNAPSHOT = 2

# Campaign types (raft/raft.go:62-71); carried in Msg.context for vote
# requests so transfer-campaigns can force past the lease check, and in
# MSG_HUP to select the campaign kind.
CAMPAIGN_NONE = 0       # normal: pre-vote first when cfg.pre_vote
CAMPAIGN_TRANSFER = 1   # leadership transfer: real election, forces the lease
CAMPAIGN_FORCE = 2      # real election even under pre_vote (post-prevote hop)

# Conf-change ops, encoded into a conf-change entry's data word.
# (reference raft.proto:145-153 ConfChangeType)
CC_ADD_NODE = 0
CC_REMOVE_NODE = 1
CC_UPDATE_NODE = 2
CC_ADD_LEARNER = 3


@dataclasses.dataclass(frozen=True)
class Spec:
    """Static shape/config parameters shared by every kernel.

    The dynamic per-run knobs (tick counts etc.) live in
    :class:`etcd_tpu.utils.config.RaftConfig`; Spec is only what determines
    array shapes and trace-time structure.
    """

    M: int = 5        # members per cluster
    L: int = 64       # log ring capacity (entries held on device per node)
    E: int = 4        # max entries carried by one MsgApp
    K: int = 4        # message slots per (sender, receiver) pair per round
    W: int = 4        # inflight window ring size (max_inflight)
    R: int = 4        # read-only request queue depth
    A: int = 8        # max committed entries applied per node per round

    def __post_init__(self):
        if self.E > self.L:
            # append_span's one-hot merge assumes one offered span never
            # wraps the ring onto itself (distinct slots per entry)
            raise ValueError(f"Spec.E ({self.E}) must be <= Spec.L ({self.L})")
        if self.M > 31:
            raise ValueError("Spec.M must fit the 5-bit conf-change id field")


# ---------------------------------------------------------------------------
# Message struct-of-arrays
# ---------------------------------------------------------------------------


class Msg(struct.PyTreeNode):
    """One message slot (all leaves scalar; batched via vmap/stacking).

    Field reuse per type (mirrors pb.Message usage, raft.proto:75-96):
      MSG_APP:       index=prevLogIndex, log_term=prevLogTerm, commit,
                     ent_len/ent_term/ent_data/ent_type = entries
      MSG_APP_RESP:  index=acked/rejected idx, reject, reject_hint, log_term=hint term
      MSG_VOTE/PRE:  index=lastIndex, log_term=lastTerm, context=campaign type
      MSG_SNAP:      index=snap index, log_term=snap term, commit=applied hash,
                     c_voters/c_voters_out/c_learners/c_learners_next = packed
                     ConfState masks, reject=auto_leave flag
      MSG_HEARTBEAT: commit=min(match, committed), context=readindex ctx
      MSG_READ_INDEX(_RESP): context=request ctx id, index=read index
      MSG_PROP:      ent_* carries proposed entries
    """

    type: jnp.ndarray      # i32
    term: jnp.ndarray      # i32 (0 == local/termless message, like reference)
    frm: jnp.ndarray       # i32 sender id
    index: jnp.ndarray     # i32
    log_term: jnp.ndarray  # i32
    commit: jnp.ndarray    # i32
    reject: jnp.ndarray    # bool
    reject_hint: jnp.ndarray  # i32
    context: jnp.ndarray   # i32
    ent_len: jnp.ndarray   # i32
    ent_term: jnp.ndarray  # i32[E]
    ent_data: jnp.ndarray  # i32[E]
    ent_type: jnp.ndarray  # i32[E]
    c_voters: jnp.ndarray        # i32 packed mask (MsgSnap)
    c_voters_out: jnp.ndarray    # i32 packed mask (MsgSnap)
    c_learners: jnp.ndarray      # i32 packed mask (MsgSnap)
    c_learners_next: jnp.ndarray # i32 packed mask (MsgSnap)


# Msg fields carrying a per-entry [E] axis (everything else is scalar).
# Shared by the flat message-tensor packing in ops/outbox.py,
# models/engine.py and models/rawnode.py — one definition so a new
# entry-shaped field can't silently mis-reshape in one of them.
ENT_FIELDS = ("ent_term", "ent_data", "ent_type")

# int16-wire exemption registry (RaftConfig.wire_int16): (field, msg type)
# pairs whose values may legally exceed int16 range because the RECEIVER
# reconstructs them from a registered split — everything else must fit the
# wire or it corrupts silently (the 81d0b1e MsgSnap hash-truncation bug
# class). engine.wire_overflow_count enforces this mechanically; register
# a split here (with the reconstruction masks at both ends) before letting
# any new wide field ride the wire.
#   MSG_SNAP.commit: full 32-bit applied hash; low 16 bits survive the
#   truncate/sign-extend round trip and the high half rides reject_hint
#   (models/raft.py MsgSnap emit + install).
WIRE_SPLIT = {("commit", MSG_SNAP)}


# [epoch, strong ref to the client the epoch was minted for] — see empty_msg
_backend_epoch: list = [0, None]


@functools.lru_cache(maxsize=64)
def _empty_msg(spec: Spec, backend_key: int) -> Msg:
    z = jnp.int32(0)
    return Msg(
        type=z, term=z, frm=jnp.int32(NONE_ID), index=z, log_term=z,
        commit=z, reject=jnp.bool_(False), reject_hint=z, context=z,
        ent_len=z,
        ent_term=jnp.zeros((spec.E,), jnp.int32),
        ent_data=jnp.zeros((spec.E,), jnp.int32),
        ent_type=jnp.zeros((spec.E,), jnp.int32),
        c_voters=z, c_voters_out=z, c_learners=z, c_learners_next=z,
    )


def empty_msg(spec: Spec) -> Msg:
    """Cached per (spec, active backend): Msg leaves are immutable and
    every caller builds variants via ``.replace``, so sharing the
    template saves ~17 device-scalar creations per host-bridged message.
    The key is a backend EPOCH (bumped whenever the live client object
    changes, compared by identity against a strong reference): a platform
    NAME would alias a re-initialised platform with its torn-down
    predecessor, and a bare id() could be reused by the allocator after
    the old client is collected."""
    import jax

    client = jax.devices()[0].client
    if client is not _backend_epoch[1]:
        _backend_epoch[0] += 1
        _backend_epoch[1] = client
    return _empty_msg(spec, _backend_epoch[0])


def pack_mask(mask: jnp.ndarray) -> jnp.ndarray:
    """bool[M] -> i32 bitmask."""
    m = mask.shape[-1]
    bits = (mask.astype(jnp.int32) << jnp.arange(m, dtype=jnp.int32))
    return bits.sum(axis=-1).astype(jnp.int32)


def unpack_mask(packed: jnp.ndarray, m: int) -> jnp.ndarray:
    """i32 bitmask -> bool[M]."""
    return ((packed[..., None] >> jnp.arange(m, dtype=jnp.int32)) & 1).astype(jnp.bool_)
