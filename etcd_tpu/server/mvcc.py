"""Multi-version KV store — the host-side applied state machine.

Mirrors the reference's ``server/storage/mvcc`` semantics with an idiomatic
Python layout (the device engine replicates *entry references*; each member
applies them to one of these stores, like each etcd node applies to its own
bbolt):

  * every write gets a ``revision{main, sub}`` (mvcc/revision.go): main
    increments once per applied txn, sub per op within it.
  * ``treeIndex`` (mvcc/index.go:25-52) maps key -> keyIndex; here a dict of
    key -> KeyIndex plus a lazily-sorted key list for range scans (bisect
    stands in for the google/btree of degree 32).
  * ``KeyIndex`` (mvcc/key_index.go:70-74) keeps *generations* separated by
    tombstones so historical reads at any revision resolve correctly.
  * reads at a revision walk the index, then fetch values from the revision-
    keyed store (the bbolt "key" bucket analog, schema/bucket.go:97).
  * compaction (mvcc/kvstore_compaction.go) drops versions <= compact_rev
    except each key's latest, and whole keys whose latest is a tombstone.

Sizes are tracked so the quota/alarm path (NOSPACE) has something to check.
"""
from __future__ import annotations

import bisect
import dataclasses


class MVCCError(Exception):
    pass


class ErrCompacted(MVCCError):
    """mvcc.ErrCompacted: requested rev <= compacted revision."""


class ErrFutureRev(MVCCError):
    """mvcc.ErrFutureRev: requested rev > current revision."""


@dataclasses.dataclass(frozen=True, order=True)
class Revision:
    main: int
    sub: int = 0


@dataclasses.dataclass
class KeyValue:
    """mvccpb.KeyValue (api/mvccpb/kv.proto)."""

    key: bytes
    value: bytes
    create_revision: int
    mod_revision: int
    version: int
    lease: int = 0


class KeyIndex:
    """key_index.go: per-key revision history in generations."""

    __slots__ = ("key", "generations")

    def __init__(self, key: bytes):
        self.key = key
        self.generations: list[list[Revision]] = []

    def put(self, rev: Revision) -> None:
        if not self.generations:
            self.generations.append([])
        self.generations[-1].append(rev)

    def tombstone(self, rev: Revision) -> None:
        self.put(rev)
        self.generations.append([])  # open a fresh (empty) generation

    def _walk(self, at_rev: int):
        """(gi, revs_visible) for the generation live at at_rev, where
        revs_visible are its revisions with main <= at_rev (key_index.go
        findGeneration + walk)."""
        for gi in range(len(self.generations) - 1, -1, -1):
            gen = self.generations[gi]
            if not gen or gen[0].main > at_rev:
                continue
            vis = [r for r in gen if r.main <= at_rev]
            if not vis:
                return None
            # closed generation whose visible tail is its tombstone => dead
            closed = gi < len(self.generations) - 1
            if closed and vis[-1] == gen[-1]:
                return None
            return gi, vis
        return None

    def get(self, at_rev: int) -> Revision | None:
        """Latest live revision <= at_rev, or None if absent/tombstoned."""
        hit = self._walk(at_rev)
        return hit[1][-1] if hit else None

    def compact(self, at_rev: int) -> bool:
        """Drop revisions <= at_rev except the live one; returns True when
        the whole keyIndex is empty and should be removed."""
        new_gens: list[list[Revision]] = []
        for gi, gen in enumerate(self.generations):
            if not gen:
                new_gens.append(gen)
                continue
            closed = gi < len(self.generations) - 1
            if closed and gen[-1].main <= at_rev:
                continue  # whole generation (incl. tombstone) compacted away
            keep = [r for r in gen if r.main > at_rev]
            live = [r for r in gen if r.main <= at_rev]
            if live and not (closed and live[-1] == gen[-1]):
                keep = [live[-1]] + keep
            new_gens.append(keep)
        # drop leading empties
        while len(new_gens) > 1 and not new_gens[0]:
            new_gens.pop(0)
        self.generations = new_gens
        return all(not g for g in self.generations)


class MVCCStore:
    """mvcc.store (kvstore.go:59-87) + treeIndex, single-writer."""

    def __init__(self):
        self.index: dict[bytes, KeyIndex] = {}
        self._sorted_keys: list[bytes] = []
        self._sorted_dirty = False
        # revision-keyed value store: (main, sub) -> KeyValue (+ tombstone flag)
        self.revs: dict[tuple[int, int], tuple[KeyValue, bool]] = {}
        self.current_rev = 1  # reference boots at rev 1 (kvstore.go:91-113)
        self.compact_rev = 0
        self.size = 0

    # -- internals ----------------------------------------------------------
    def _keys(self) -> list[bytes]:
        if self._sorted_dirty:
            self._sorted_keys = sorted(self.index.keys())
            self._sorted_dirty = False
        return self._sorted_keys

    def _range_keys(self, key: bytes, range_end: bytes | None) -> list[bytes]:
        """etcd range semantics: range_end None => single key; b'\\0' =>
        from key to end; else half-open [key, range_end)."""
        if range_end is None:
            return [key] if key in self.index else []
        ks = self._keys()
        lo = bisect.bisect_left(ks, key)
        if range_end == b"\x00":
            return ks[lo:]
        hi = bisect.bisect_left(ks, range_end)
        return ks[lo:hi]

    def _check_rev(self, rev: int) -> int:
        if rev <= 0 or rev > self.current_rev:
            if rev > self.current_rev:
                raise ErrFutureRev(rev)
            return self.current_rev
        if rev < self.compact_rev:
            raise ErrCompacted(rev)
        return rev

    # -- txn API (kvstore_txn.go) -------------------------------------------
    def write_txn(self) -> "WriteTxn":
        return WriteTxn(self)

    def range(
        self,
        key: bytes,
        range_end: bytes | None = None,
        rev: int = 0,
        limit: int = 0,
        count_only: bool = False,
    ) -> tuple[list[KeyValue], int, int]:
        """(kvs, count, rev_used). rev=0 means current."""
        at = self._check_rev(rev if rev > 0 else self.current_rev)
        return self._range_at(at, key, range_end, limit, count_only)

    def _range_at(
        self,
        at: int,
        key: bytes,
        range_end: bytes | None = None,
        limit: int = 0,
        count_only: bool = False,
    ) -> tuple[list[KeyValue], int, int]:
        kvs: list[KeyValue] = []
        count = 0
        for k in self._range_keys(key, range_end):
            ki = self.index.get(k)
            if ki is None:
                continue
            r = ki.get(at)
            if r is None:
                continue
            count += 1
            if count_only:
                continue
            if limit and len(kvs) >= limit:
                continue
            kv, tomb = self.revs[(r.main, r.sub)]
            if not tomb:
                kvs.append(kv)
        return kvs, count, at

    def compact(self, rev: int) -> None:
        if rev <= self.compact_rev:
            raise ErrCompacted(rev)
        if rev > self.current_rev:
            raise ErrFutureRev(rev)
        self.compact_rev = rev
        dead_keys = []
        for k, ki in self.index.items():
            if ki.compact(rev):
                dead_keys.append(k)
        for k in dead_keys:
            del self.index[k]
        self._sorted_dirty = True
        keep = set()
        for ki in self.index.values():
            for gen in ki.generations:
                for r in gen:
                    keep.add((r.main, r.sub))
        for rk in [rk for rk in self.revs if rk[0] <= rev and rk not in keep]:
            kv, _ = self.revs.pop(rk)
            self.size -= len(kv.key) + len(kv.value)

    def hash_kv(self, rev: int = 0) -> int:
        """Maintenance/HashKV analog (mvcc/hash.go): order-independent
        digest of revision data up to rev, folded with the canonical
        mixing kernel shared with the device apply plane
        (device_mvcc/scheme.py) — the corruption checker, the chaos
        report and the device plane's equivalence checks all compare
        digests built from the same fold."""
        from etcd_tpu.device_mvcc import scheme

        at = rev if rev > 0 else self.current_rev
        s = 0
        for (main, sub), (kv, tomb) in self.revs.items():
            if main > at:
                continue
            s = scheme.u32(s + scheme.u32(scheme.history_record_mix(
                main, sub, scheme.u32(scheme.bytes32(kv.key)),
                scheme.u32(scheme.bytes32(kv.value)), tomb,
            )))
        return scheme.u32(s * scheme.MIX_C + at * scheme.MIX_D + scheme.MIX_A)

    def hash_kv_latest(self, nkeys: int) -> int:
        """The canonical latest-record digest over the device key space —
        bit-equal to the device plane's ``kv_digest`` lane for a store
        that applied the same committed words (scheme.store_latest_digest;
        the differential-fuzz parity gate)."""
        from etcd_tpu.device_mvcc import scheme

        return scheme.store_latest_digest(self, nkeys)

    # -- snapshot (Maintenance.Snapshot / etcdutl analog) --------------------
    def to_snapshot(self) -> dict:
        return {
            "current_rev": self.current_rev,
            "compact_rev": self.compact_rev,
            "revs": [
                (m, s, kv.key, kv.value, kv.create_revision, kv.mod_revision,
                 kv.version, kv.lease, tomb)
                for (m, s), (kv, tomb) in sorted(self.revs.items())
            ],
            "index": [
                (k, [[(r.main, r.sub) for r in gen] for gen in ki.generations])
                for k, ki in sorted(self.index.items())
            ],
        }

    @classmethod
    def from_snapshot(cls, snap: dict) -> "MVCCStore":
        st = cls()
        st.current_rev = snap["current_rev"]
        st.compact_rev = snap["compact_rev"]
        for m, s, k, v, cr, mr, ver, lease, tomb in snap["revs"]:
            st.revs[(m, s)] = (KeyValue(k, v, cr, mr, ver, lease), tomb)
            st.size += len(k) + len(v)
        for k, gens in snap["index"]:
            ki = KeyIndex(k)
            ki.generations = [[Revision(m, s) for m, s in gen] for gen in gens]
            st.index[k] = ki
        st._sorted_dirty = True
        return st


class DeviceBackedStore:
    """MVCCStore-shaped facade over one lane of the device-resident apply
    plane (etcd_tpu/device_mvcc) — the \"thin host facade over device
    state\" the apply-plane refactor calls for: the authoritative revision
    store lives on device as ``[keys, C]`` tensors; this class only
    encodes ops into int32 words, dispatches one jitted masked apply, and
    materializes KeyValue/Event objects from lane readbacks.

    Contract differences from the host store (all inherent to the
    latest-record layout, and documented rather than papered over):

      * keys/values must be canonical (scheme.key_bytes/encode_value);
        anything else raises ValueError before touching the device.
      * lease ids ride a 4-bit word field (0..15).
      * historical reads: a matching key whose mod_revision is above the
        requested rev raises ErrCompacted — the plane's effective per-key
        compaction floor is its latest record (see device_mvcc.apply
        .read_at). Reads at the current revision are always exact.
      * ``revs`` exposes the latest record per key (revision-coalesced
        history): watcher catch-up replays coalesced deltas, the same
        delivery contract as the device watch scan.
      * ``size`` counts live latest records (quota/status accounting),
        not retained history bytes.
    """

    def __init__(self, plane, lane: int = 0):
        from etcd_tpu.device_mvcc import scheme

        self.plane = plane
        self.lane = lane
        self._scheme = scheme

    # -- cursors -------------------------------------------------------------
    @property
    def current_rev(self) -> int:
        return self.plane.current_rev(self.lane)

    @property
    def compact_rev(self) -> int:
        return self.plane.compact_rev(self.lane)

    @property
    def size(self) -> int:
        sc = self._scheme
        n = 0
        for kid, r in self.plane.records(self.lane).items():
            n += len(sc.key_bytes(kid))
            if not r["tomb"]:
                n += len(sc.encode_value(r["vword"]))
        return n

    # -- record materialization ---------------------------------------------
    def _kv(self, kid: int, r: dict) -> KeyValue:
        sc = self._scheme
        if r["tomb"]:
            return KeyValue(sc.key_bytes(kid), b"", 0, r["mod"], 0)
        return KeyValue(sc.key_bytes(kid), sc.encode_value(r["vword"]),
                        r["create"], r["mod"], r["version"], r["lease"])

    def _rev_keyed(self) -> dict:
        """Latest record per key, keyed (mod, sub) — records sharing one
        main (a multi-op txn, or one delete-range over several keys) get
        distinct subs in key-id order, so none collide. The device never
        materializes subs; key-id order is the one deterministic
        assignment both readers of this view (watcher catch-up,
        snapshot materialization) can agree on."""
        records = self.plane.records(self.lane)
        by_main: dict[int, int] = {}
        out = {}
        for kid in sorted(records):
            r = records[kid]
            sub = by_main.get(r["mod"], 0)
            by_main[r["mod"]] = sub + 1
            out[(r["mod"], sub)] = (self._kv(kid, r), r["tomb"])
        return out

    @property
    def revs(self) -> dict:
        """The coalesced history view WatchableStore's catch-up path
        reads (latest record per key; see _rev_keyed)."""
        return self._rev_keyed()

    def _key_range(self, key: bytes, range_end: bytes | None) -> tuple[int, int]:
        sc = self._scheme
        lo = sc.key_id(key)
        if range_end is None:
            return lo, lo + 1
        if range_end == b"\x00":
            return lo, self.plane.kvspec.keys
        return lo, sc.key_id(range_end)

    # -- txn / read API (MVCCStore surface) ----------------------------------
    def write_txn(self) -> "DeviceWriteTxn":
        return DeviceWriteTxn(self)

    def range(self, key: bytes, range_end: bytes | None = None, rev: int = 0,
              limit: int = 0, count_only: bool = False):
        cur = self.current_rev
        at = rev if rev > 0 else cur
        if at > cur:
            raise ErrFutureRev(at)
        if at < self.compact_rev:
            raise ErrCompacted(at)
        lo, hi = self._key_range(key, range_end)
        kvs: list[KeyValue] = []
        count = 0
        records = self.plane.records(self.lane)
        for kid in sorted(records):
            if not lo <= kid < hi:
                continue
            r = records[kid]
            if r["mod"] > at:
                # latest-record store: this key's state at `at` was
                # compacted-to-latest by construction — refuse rather
                # than serve the newer record as history
                raise ErrCompacted(at)
            if r["tomb"]:
                continue
            count += 1
            if count_only or (limit and len(kvs) >= limit):
                continue
            kvs.append(self._kv(kid, r))
        return kvs, count, at

    def compact(self, rev: int) -> None:
        if rev <= self.compact_rev:
            raise ErrCompacted(rev)
        if rev > self.current_rev:
            raise ErrFutureRev(rev)
        self.plane.apply_word_lane(self.lane, self._scheme.encode_compact(rev))

    # -- digests -------------------------------------------------------------
    def hash_kv(self, rev: int = 0) -> int:
        """The canonical device digest (scheme.latest_digest) — the same
        int32 the differential-fuzz gate compares; rev is accepted for
        interface parity but only the current revision is served."""
        if rev > self.current_rev:
            raise ErrFutureRev(rev)
        return self.plane.digest(self.lane)

    # -- snapshots (materialized through the host store) ---------------------
    def _materialize(self) -> MVCCStore:
        """Latest records as a single-generation host MVCCStore (the
        snapshot donor form; history below the latest record does not
        exist on device, so none is invented)."""
        st = MVCCStore()
        st.current_rev = self.current_rev
        st.compact_rev = self.compact_rev
        for (mod, sub), (kv, tomb) in self._rev_keyed().items():
            ki = KeyIndex(kv.key)
            if tomb:
                ki.tombstone(Revision(mod, sub))
            else:
                ki.put(Revision(mod, sub))
            st.index[kv.key] = ki
            st.revs[(mod, sub)] = (kv, tomb)
            st.size += len(kv.key) + len(kv.value)
        st._sorted_dirty = True
        return st

    def to_snapshot(self) -> dict:
        return self._materialize().to_snapshot()

    def load_snapshot(self, snap: dict) -> None:
        """Install a snapshot into the device lane (the applySnapshot
        path for the device plane)."""
        sc = self._scheme
        host = MVCCStore.from_snapshot(snap)
        records = {}
        for (kid, mod, create, version, vword, lease, tomb) in (
                sc.store_latest_records(host, self.plane.kvspec.keys)):
            records[kid] = {"mod": mod, "create": create, "version": version,
                           "vword": vword, "lease": lease, "tomb": tomb}
        self.plane.load_lane(self.lane, records, host.current_rev,
                             host.compact_rev)


class DeviceWriteTxn:
    """WriteTxn facade over the device lane: ops dispatch eagerly,
    word-by-word, with the CONT bit joining them into one device txn
    (same revision main) — so intra-txn read-your-writes falls out of
    reading the live device state, exactly like the host txn's buffer
    visibility. Events are built from pre/post lane readbacks."""

    def __init__(self, store: DeviceBackedStore):
        self.s = store
        self.events: list[tuple[str, KeyValue, KeyValue | None]] = []
        self._started = False
        self.main = store.current_rev + 1

    def _prev(self, kid: int) -> KeyValue | None:
        r = self.s.plane.records(self.s.lane).get(kid)
        if r is None or r["tomb"]:
            return None
        return self.s._kv(kid, r)

    def range(self, key: bytes, range_end: bytes | None = None,
              limit: int = 0, count_only: bool = False):
        # eager application means the live lane IS the txn's view
        return self.s.range(key, range_end, 0, limit, count_only)

    def put(self, key: bytes, value: bytes, lease: int = 0) -> int:
        sc = self.s._scheme
        kid = sc.key_id(key)
        if kid >= self.s.plane.kvspec.keys:
            # validate BEFORE dispatch: the device op would stamp a
            # phantom revision with no key slot to land on
            raise ValueError(
                f"key {key!r} outside the device key space "
                f"(keys={self.s.plane.kvspec.keys})"
            )
        word = sc.encode_put(kid, sc.decode_value(value), lease,
                             cont=self._started)
        prev = self._prev(kid)
        self.s.plane.apply_word_lane(self.s.lane, word)
        self._started = True
        r = self.s.plane.records(self.s.lane)[kid]
        kv = self.s._kv(kid, r)
        self.events.append(("put", kv, prev))
        return kv.mod_revision

    def delete_range(self, key: bytes, range_end: bytes | None = None) -> int:
        sc = self.s._scheme
        lo, hi = self.s._key_range(key, range_end)
        pre = {
            kid: r for kid, r in self.s.plane.records(self.s.lane).items()
            if lo <= kid < hi and not r["tomb"]
        }
        if not pre:
            return 0
        word = sc.encode_delete_range(lo, min(hi, (1 << sc.HI_BITS) - 1),
                                      cont=self._started)
        self.s.plane.apply_word_lane(self.s.lane, word)
        self._started = True
        post = self.s.plane.records(self.s.lane)
        for kid in sorted(pre):
            kv = self.s._kv(kid, post[kid])
            self.events.append(("delete", kv, self.s._kv(kid, pre[kid])))
        return len(pre)

    def end(self) -> int:
        # the device bumped current_rev per writing word already
        return self.s.current_rev


class WriteTxn:
    """One applied entry's write transaction: all ops share revision main =
    current_rev + 1, distinct subs (kvstore_txn.go:127-240); End() bumps
    current_rev and reports events for the watch layer
    (watchable_store_txn.go:22)."""

    def __init__(self, store: MVCCStore):
        self.s = store
        self.main = store.current_rev + 1
        self.sub = 0
        self.events: list[tuple[str, KeyValue, KeyValue | None]] = []
        self._wrote = False

    def range(self, key: bytes, range_end: bytes | None = None,
              limit: int = 0, count_only: bool = False):
        """Read *inside* the txn: sees this txn's own earlier writes
        (kvstore_txn.go's read buffer over the uncommitted batch)."""
        return self.s._range_at(self.main, key, range_end, limit, count_only)

    def put(self, key: bytes, value: bytes, lease: int = 0) -> int:
        s = self.s
        rev = Revision(self.main, self.sub)
        ki = s.index.get(key)
        if ki is None:
            ki = KeyIndex(key)
            s.index[key] = ki
            s._sorted_dirty = True
        # visibility at self.main: ops in this txn see earlier ops of the
        # same txn (intra-txn read-your-writes, kvstore_txn.go tx buffer).
        # create/version come from the previous RECORD, not an index walk:
        # the reference stores them in the KeyValue and restores the
        # keyIndex generation's (created, ver) from it (kvstore.go
        # restore + key_index.go generation{created, ver}), so they
        # survive compaction — an index-walk derivation regressed both
        # once compaction dropped the generation's older revisions (and
        # diverged from the device apply plane, whose latest-record store
        # is exactly the reference's record-carried semantics).
        prev_kv = None
        pr = ki.get(self.main)
        if pr is not None:
            prev_kv = s.revs[(pr.main, pr.sub)][0]
        if prev_kv is None:
            create, version = rev.main, 1
        else:
            create, version = prev_kv.create_revision, prev_kv.version + 1
        ki.put(rev)
        kv = KeyValue(key, value, create, rev.main, version, lease)
        s.revs[(rev.main, rev.sub)] = (kv, False)
        s.size += len(key) + len(value)
        self.events.append(("put", kv, prev_kv))
        self.sub += 1
        self._wrote = True
        return rev.main

    def delete_range(self, key: bytes, range_end: bytes | None = None) -> int:
        s = self.s
        deleted = 0
        for k in list(s._range_keys(key, range_end)):
            ki = s.index.get(k)
            if ki is None:
                continue
            live = ki.get(self.main)  # sees this txn's own writes
            if live is None:
                continue
            rev = Revision(self.main, self.sub)
            prev_kv = s.revs[(live.main, live.sub)][0]
            ki.tombstone(rev)
            kv = KeyValue(k, b"", 0, rev.main, 0)
            s.revs[(rev.main, rev.sub)] = (kv, True)
            self.events.append(("delete", kv, prev_kv))
            self.sub += 1
            deleted += 1
            self._wrote = True
        return deleted

    def end(self) -> int:
        if self._wrote:
            self.s.current_rev = self.main
        return self.s.current_rev
