"""Differential fuzz harness: device plane vs host MVCCStore.

Generates randomized txn schedules (puts, multi-op CONT txns, point /
interval / to-end delete-ranges, valid and deliberately-invalid
compactions), applies every schedule to BOTH planes — the device via one
batched ``apply_words`` over ``[ops, groups]`` (each group is its own
schedule: the groups axis carries schedule diversity), the host by
replaying each column through ``MVCCStore``/``WriteTxn`` — and compares:

  * the shared canonical digest (scheme.store_latest_digest vs
    apply.kv_digest) — the headline hash_kv parity gate,
  * revision bookkeeping (current_rev / compact_rev),
  * compaction-boundary errors (host ErrCompacted/ErrFutureRev exception
    counts vs the device status lanes),
  * per-key latest records, field by field.

Shared by tests/test_device_mvcc.py (fast + 4096-group acceptance
shapes) and chaos_run.py's APPLY_* self-check tier.
"""
from __future__ import annotations

import numpy as np

from etcd_tpu.device_mvcc import scheme
from etcd_tpu.device_mvcc.apply import apply_words, kv_digest
from etcd_tpu.device_mvcc.state import KVSpec, init_kv


def gen_schedules(kvspec: KVSpec, groups: int, ops: int,
                  seed: int = 0) -> np.ndarray:
    """[ops, groups] int32 word matrix; each column an independent
    schedule. Mix: ~55% puts (some opening multi-op CONT txns), ~25%
    delete-ranges, ~20% compactions (split valid / below-floor /
    future)."""
    rng = np.random.default_rng(seed)
    K = kvspec.keys
    words = np.zeros((ops, groups), np.int32)
    for g in range(groups):
        cur = 1  # tracked optimistically (puts always bump); only used to
        # steer compaction revs toward interesting boundaries — exactness
        # is not required, invalid picks just exercise the error lanes
        cont_open = False
        for i in range(ops):
            r = rng.random()
            if r < 0.55:
                cont = cont_open and rng.random() < 0.5
                words[i, g] = scheme.encode_put(
                    int(rng.integers(K)), int(rng.integers(scheme.MAX_VAL + 1)),
                    int(rng.integers(scheme.MAX_LEASE + 1)), cont=cont,
                )
                if not cont:
                    cur += 1
                # ~30% of puts open a txn the next op may continue
                cont_open = rng.random() < 0.3
            elif r < 0.8:
                lo = int(rng.integers(K))
                kind = rng.random()
                if kind < 0.5:
                    hi = lo + 1                      # point delete
                elif kind < 0.8:
                    hi = int(rng.integers(lo, K)) + 1  # interval
                else:
                    hi = K                           # from lo to end
                cont = cont_open and rng.random() < 0.3
                words[i, g] = scheme.encode_delete_range(lo, hi, cont=cont)
                if not cont:
                    cur += 1
                cont_open = False
            else:
                kind = rng.random()
                if kind < 0.6:
                    rev = max(1, cur - int(rng.integers(1, 6)))  # plausible
                elif kind < 0.8:
                    rev = int(rng.integers(0, max(2, cur // 2)))  # often old
                else:
                    rev = cur + int(rng.integers(1, 50))  # future -> error
                words[i, g] = scheme.encode_compact(min(
                    rev, scheme.MAX_COMPACT_REV))
                # a compact closes the txn; sometimes leave cont_open set
                # anyway so schedules exercise the CONT-with-no-open-txn
                # guard (apply_word opens a fresh txn, like host replay)
                cont_open = rng.random() < 0.2
    return words


def host_replay(kvspec: KVSpec, column: np.ndarray):
    """Replay one schedule column through the host plane. Returns
    (store, err_compacted, err_future) — exceptions become counts, the
    host twin of the device status lanes."""
    from etcd_tpu.server.mvcc import ErrCompacted, ErrFutureRev, MVCCStore

    store = MVCCStore()
    err_c = err_f = 0
    txn = None
    for word in column:
        op = scheme.decode(int(word))
        kind = op["kind"]
        if kind == scheme.KIND_NOP:
            continue
        if kind == scheme.KIND_COMPACT:
            if txn is not None:
                txn.end()
                txn = None
            try:
                store.compact(op["rev"])
            except ErrCompacted:
                err_c += 1
            except ErrFutureRev:
                err_f += 1
            continue
        if txn is None or not op["cont"]:
            if txn is not None:
                txn.end()
            txn = store.write_txn()
        if kind == scheme.KIND_PUT:
            txn.put(scheme.key_bytes(op["key"]), scheme.encode_value(op["val"]),
                    op["lease"])
        else:
            lo, hi = op["lo"], op["hi"]
            if hi >= kvspec.keys:
                range_end = b"\x00" if lo < kvspec.keys else None
                if lo >= kvspec.keys:
                    continue
            else:
                range_end = scheme.key_bytes(hi)
            if hi == lo + 1:
                range_end = None  # point delete, host single-key path
            txn.delete_range(scheme.key_bytes(lo), range_end)
    if txn is not None:
        txn.end()
    return store, err_c, err_f


def differential_run(kvspec: KVSpec, groups: int, ops: int, seed: int = 0,
                     check_groups: int | None = None) -> dict:
    """One batched device run vs per-column host replays.

    ``check_groups``: how many columns to replay host-side (None = all).
    Returns a report dict with mismatch counts (all-zero = parity)."""
    import jax

    words = gen_schedules(kvspec, groups, ops, seed)
    st = jax.jit(
        lambda s, w: apply_words(kvspec, s, w)
    )(init_kv(kvspec, groups), words)
    dig = np.asarray(kv_digest(kvspec, st))
    cur = np.asarray(st.current_rev)
    cmp_ = np.asarray(st.compact_rev)
    ec = np.asarray(st.err_compacted)
    ef = np.asarray(st.err_future)
    sub = jax.tree.map(np.asarray, st)

    n = groups if check_groups is None else min(check_groups, groups)
    rep = {
        "groups": groups, "ops": ops, "seed": seed, "checked": n,
        "digest_mismatches": 0, "rev_mismatches": 0, "err_mismatches": 0,
        "record_mismatches": 0,
    }
    for g in range(n):
        store, herr_c, herr_f = host_replay(kvspec, words[:, g])
        if scheme.store_latest_digest(store, kvspec.keys) != int(dig[g]):
            rep["digest_mismatches"] += 1
        if (store.current_rev, store.compact_rev) != (int(cur[g]),
                                                      int(cmp_[g])):
            rep["rev_mismatches"] += 1
        if (herr_c, herr_f) != (int(ec[g]), int(ef[g])):
            rep["err_mismatches"] += 1
        host = {k: (m, c, v, w, le, t) for
                (k, m, c, v, w, le, t) in scheme.store_latest_records(
                    store, kvspec.keys)}
        dev = {}
        for kid in np.nonzero(sub.present[:, g])[0]:
            kid = int(kid)
            if sub.tomb[kid, g]:
                dev[kid] = (int(sub.mod[kid, g]), 0, 0, 0, 0, True)
            else:
                dev[kid] = (int(sub.mod[kid, g]), int(sub.create[kid, g]),
                            int(sub.version[kid, g]), int(sub.vword[kid, g]),
                            int(sub.lease[kid, g]), False)
        if host != dev:
            rep["record_mismatches"] += 1
    rep["parity_ok"] = not any(
        rep[k] for k in ("digest_mismatches", "rev_mismatches",
                         "err_mismatches", "record_mismatches")
    )
    return rep
