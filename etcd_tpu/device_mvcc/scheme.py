"""Canonical KV scheme shared by the host and device apply planes.

The device-resident MVCC plane (this package) and the host ``MVCCStore``
(etcd_tpu/server/mvcc.py) must agree on three things for the differential
checks — and the end-to-end served-writes story — to be meaningful:

  1. the **key space**: device keys are slot ids ``0..keys-1``; the host
     sees them as canonical byte keys (:func:`key_bytes`).  The mapping is
     bijective, so etcd range semantics over canonical keys coincide with
     interval masks over slot ids.
  2. the **value space**: device values are fixed-width *value words* (the
     payloadRef scheme of SURVEY.md §7 applied to values: the replicated
     word IS the value reference); the host stores the canonical byte
     encoding (:func:`encode_value`).  Both directions are exact.
  3. the **digest**: one record-fold (:func:`record_mix` /
     :func:`latest_digest`) computed identically by the host (pure-python
     ints, here) and the device (the jnp twin in
     ``etcd_tpu/device_mvcc/apply.py:kv_digest``).  The fold is a
     wrap-sum of per-record mixes, so it is order-independent — the device
     reduces over the key axis in one pass, the host iterates dicts — and
     every equivalence check (fuzz suite, chaos_run's APPLY tier, the
     corruption checker) compares literally the same int32.

``MVCCStore.hash_kv`` also routes its (full-history) digest through
:func:`history_record_mix`, so the host plane's corruption/chaos reporting
and the device plane's latest-record digest share one mixing kernel — a
new field added to one plane's records without the other fails the
cross-check in tests/test_device_mvcc.py instead of silently diverging.

This module is dependency-free on purpose (no jax, no server imports):
it sits below both planes in the layering.
"""
from __future__ import annotations

import zlib

# ---------------------------------------------------------------------------
# int32 arithmetic (two's complement, congruent with jnp.int32 wrap)
# ---------------------------------------------------------------------------

_M32 = 0xFFFFFFFF

# mixing constants — shared with the jnp twin in apply.py (imported there;
# change them here and both planes move together)
MIX_A = 0x9E3779B1  # 2654435761, Knuth multiplicative
MIX_B = 0x85EBCA77  # murmur3 c2
MIX_C = 1000003     # the repo's rolling-hash base (models/raft.py _mix_hash)
MIX_D = 69069       # VAX MTH$RANDOM multiplier
MIX_E = 40503       # 16-bit Fibonacci hashing constant


def u32(x: int) -> int:
    return x & _M32


def i32(x: int) -> int:
    """Two's-complement int32 view of x (matches a jnp.int32 bit pattern)."""
    x &= _M32
    return x - 0x1_0000_0000 if x >= 0x8000_0000 else x


# ---------------------------------------------------------------------------
# op-word codec (bit layout shared with the device decoder)
# ---------------------------------------------------------------------------
#
# A device-MVCC operation is ONE int32 entry word — the unit the consensus
# tier replicates.  Layout (low bit first):
#
#   [0:2]   kind        0=nop  1=put  2=delete-range  3=compact
#   [2]     cont        1 = this op continues the previous word's txn
#                       (same revision main, next sub) — the multi-op-txn
#                       encoding; the engine's apply frontier never sets it
#   [3:12]  key         slot id (put: the key; delete: range lo)
#   [12:24] val         put: value word
#   [24:28] lease       put: lease id (0 = none)
#   [12:22] hi          delete: exclusive range end (lo+1 = point delete,
#                       kvspec.keys = from-lo-to-end)
#   [3:28]  rev         compact: compaction revision
#
# Words stay < 2**28: always positive int32, and safely outside the
# conf-change bit window (bits 16-20) only in the sense that they are
# ENTRY_NORMAL — the apply plane masks on entry type, not bit patterns.
# KV words do NOT fit the int16 wire; device-apply runs require
# wire_int16=False exactly like the membership chaos tier.

KIND_NOP = 0
KIND_PUT = 1
KIND_DELETE = 2
KIND_COMPACT = 3

KEY_SHIFT, KEY_BITS = 3, 9
VAL_SHIFT, VAL_BITS = 12, 12
LEASE_SHIFT, LEASE_BITS = 24, 4
HI_SHIFT, HI_BITS = 12, 10
REV_SHIFT, REV_BITS = 3, 25

MAX_KEYS = (1 << KEY_BITS) - 1          # 511 key slots
MAX_VAL = (1 << VAL_BITS) - 1
MAX_LEASE = (1 << LEASE_BITS) - 1
MAX_COMPACT_REV = (1 << REV_BITS) - 1

CONT_BIT = 1 << 2


def encode_put(key: int, val: int, lease: int = 0, cont: bool = False) -> int:
    if not 0 <= key <= MAX_KEYS:
        raise ValueError(f"key {key} outside [0, {MAX_KEYS}]")
    if not 0 <= val <= MAX_VAL:
        raise ValueError(f"value word {val} outside [0, {MAX_VAL}]")
    if not 0 <= lease <= MAX_LEASE:
        raise ValueError(f"lease {lease} outside [0, {MAX_LEASE}]")
    return (
        KIND_PUT | (CONT_BIT if cont else 0)
        | (key << KEY_SHIFT) | (val << VAL_SHIFT) | (lease << LEASE_SHIFT)
    )


def encode_delete_range(lo: int, hi: int, cont: bool = False) -> int:
    """Tombstone live keys in [lo, hi). hi = lo+1 is a point delete."""
    if not 0 <= lo <= MAX_KEYS:
        raise ValueError(f"lo {lo} outside [0, {MAX_KEYS}]")
    if not 0 <= hi <= (1 << HI_BITS) - 1:
        raise ValueError(f"hi {hi} outside [0, {(1 << HI_BITS) - 1}]")
    return (
        KIND_DELETE | (CONT_BIT if cont else 0)
        | (lo << KEY_SHIFT) | (hi << HI_SHIFT)
    )


def encode_compact(rev: int) -> int:
    if not 0 <= rev <= MAX_COMPACT_REV:
        raise ValueError(f"rev {rev} outside [0, {MAX_COMPACT_REV}]")
    return KIND_COMPACT | (rev << REV_SHIFT)


def decode(word: int) -> dict:
    """Host-side decode (tests / debugging / host replay)."""
    kind = word & 3
    out = {"kind": kind, "cont": bool(word & CONT_BIT)}
    if kind == KIND_PUT:
        out["key"] = (word >> KEY_SHIFT) & MAX_KEYS
        out["val"] = (word >> VAL_SHIFT) & MAX_VAL
        out["lease"] = (word >> LEASE_SHIFT) & MAX_LEASE
    elif kind == KIND_DELETE:
        out["lo"] = (word >> KEY_SHIFT) & MAX_KEYS
        out["hi"] = (word >> HI_SHIFT) & ((1 << HI_BITS) - 1)
    elif kind == KIND_COMPACT:
        out["rev"] = (word >> REV_SHIFT) & MAX_COMPACT_REV
    return out


# ---------------------------------------------------------------------------
# canonical key/value byte encodings (the host plane's view)
# ---------------------------------------------------------------------------


def key_bytes(key_id: int) -> bytes:
    """Canonical byte key for a device key slot (sorted order == id order,
    so etcd range semantics coincide with slot-interval masks)."""
    return b"k%03d" % key_id


def key_id(key: bytes) -> int:
    """Inverse of :func:`key_bytes`; raises ValueError off the canonical
    key space (the device plane serves ONLY canonical keys)."""
    if len(key) == 4 and key[:1] == b"k" and key[1:].isdigit():
        kid = int(key[1:])
        if key_bytes(kid) == key:
            return kid
    raise ValueError(f"key {key!r} is not in the canonical device key space")


def encode_value(val: int) -> bytes:
    return b"v%d" % val


def decode_value(value: bytes) -> int:
    if value[:1] == b"v" and value[1:].isdigit():
        return int(value[1:])
    raise ValueError(f"value {value!r} is not a canonical device value word")


def value_hash32(val: int) -> int:
    """int32 mix of a value word — cheap enough for the device to compute
    inline (no byte hashing: the word IS the value reference)."""
    return i32(u32(val * MIX_A) ^ u32(val + MIX_B))


# ---------------------------------------------------------------------------
# the shared record fold
# ---------------------------------------------------------------------------


def record_mix(key: int, mod: int, create: int, version: int, vword: int,
               lease: int, tomb: bool) -> int:
    """Mix of one latest-record per-key tuple. The jnp twin
    (device_mvcc/apply.py:_record_mix) MUST stay line-for-line congruent —
    tests/test_device_mvcc.py cross-checks them on random records."""
    h = u32(key * MIX_A + mod * MIX_B)
    h = u32(h ^ u32(create * MIX_C + version * MIX_D + 7))
    h = u32(h * MIX_C + (u32(value_hash32(vword)) ^ u32(lease * MIX_E)))
    if tomb:
        h = u32(h + MIX_D)
    return i32(h)


def latest_digest(records, current_rev: int, compact_rev: int) -> int:
    """Order-independent digest over latest-record tuples
    ``(key, mod, create, version, vword, lease, tomb)`` plus the store's
    revision cursors. The device twin is ``apply.kv_digest``."""
    s = 0
    for (key, mod, create, version, vword, lease, tomb) in records:
        s = u32(s + u32(record_mix(key, mod, create, version, vword, lease,
                                   tomb)))
    h = u32(s * MIX_C + current_rev * MIX_A)
    h = u32(h ^ u32(compact_rev * MIX_E + MIX_B))
    return i32(h)


def history_record_mix(main: int, sub: int, key32: int, val32: int,
                       tomb: bool) -> int:
    """Mix of one full-history revision record — the kernel behind
    ``MVCCStore.hash_kv``. Shares the constants (and so the bit-level
    mixing discipline) with :func:`record_mix`; key/value bytes arrive
    pre-hashed (:func:`bytes32`) because the device never folds raw
    bytes."""
    h = u32(main * MIX_A + sub * MIX_B)
    h = u32(h ^ u32(key32 * MIX_C + val32 * MIX_D + 7))
    if tomb:
        h = u32(h + MIX_E)
    return i32(h)


def bytes32(b: bytes) -> int:
    """Canonical bytes -> int32 (crc32; host-only — device values are
    words, never raw bytes)."""
    return i32(zlib.crc32(b))


# ---------------------------------------------------------------------------
# host-store helpers (duck-typed over MVCCStore; no import to keep
# layering acyclic: scheme <- {server.mvcc, device_mvcc.apply, tests})
# ---------------------------------------------------------------------------


def store_latest_records(store, nkeys: int):
    """Latest-record tuples for the canonical key slots of a host
    ``MVCCStore`` — the host-side view of the device revision store.
    A key's latest record is the newest revision in its keyIndex
    (tombstones included until compaction removes the whole key, exactly
    like the device's tombstone mask)."""
    out = []
    for kid in range(nkeys):
        ki = store.index.get(key_bytes(kid))
        if ki is None:
            continue
        last = None
        for gen in ki.generations:
            if gen:
                last = gen[-1]
        if last is None:
            continue
        kv, tomb = store.revs[(last.main, last.sub)]
        if tomb:
            out.append((kid, last.main, 0, 0, 0, 0, True))
        else:
            out.append((kid, kv.mod_revision, kv.create_revision, kv.version,
                        decode_value(kv.value), kv.lease, False))
    return out


def store_latest_digest(store, nkeys: int) -> int:
    """The canonical latest-record digest of a host store — MUST equal the
    device plane's ``kv_digest`` lane after applying the same words."""
    return latest_digest(
        store_latest_records(store, nkeys), store.current_rev,
        store.compact_rev,
    )
