"""Request-scoped tracing — the pkg/traceutil analog.

The reference threads a ``traceutil.Trace`` through the apply path
(`pkg/traceutil/trace.go:56-75` Trace/step, used from Put/Txn/Range at
`server/etcdserver/v3_server.go:602-610` and `mvcc/kvstore_txn.go`): each
request records named steps with timestamps and extra fields, and the
whole timeline is logged when total duration crosses a threshold. Device
rounds never trace per node (that would serialize the fleet); tracing
covers the HOST request pipeline: propose -> wait-applied -> apply ->
respond.
"""
from __future__ import annotations

import time


class Field:
    """traceutil.Field (trace.go:33-40)."""

    __slots__ = ("key", "value")

    def __init__(self, key: str, value):
        self.key = key
        self.value = value

    def format(self) -> str:
        return f"{self.key}:{self.value}; "


def _write_fields(fields) -> str:
    if not fields:
        return ""
    return "{" + "".join(f.format() for f in fields) + "}"


class Trace:
    """traceutil.Trace (trace.go:56-75): an operation with timestamped
    steps, dumped through the process logger if it ran long."""

    def __init__(self, operation: str, *fields: Field):
        self.operation = operation
        self.fields = list(fields)
        self.start_time = time.perf_counter()
        self.steps: list[tuple[float, str, tuple[Field, ...]]] = []
        self.is_empty = False

    @classmethod
    def todo(cls) -> "Trace":
        """traceutil.TODO: a non-nil, inert trace (trace.go:77-80)."""
        t = cls("")
        t.is_empty = True
        return t

    def step(self, msg: str, *fields: Field) -> None:
        if not self.is_empty:
            self.steps.append((time.perf_counter(), msg, fields))

    def add_field(self, *fields: Field) -> None:
        """Set-or-replace by key (trace.go AddField semantics)."""
        for f in fields:
            for i, old in enumerate(self.fields):
                if old.key == f.key:
                    self.fields[i] = f
                    break
            else:
                self.fields.append(f)

    def duration(self) -> float:
        return time.perf_counter() - self.start_time

    def format(self) -> str:
        """The dump layout of trace.go logInfo: header + per-step lines
        with deltas."""
        total_ms = self.duration() * 1e3
        lines = [
            f'trace[{id(self) & 0xFFFFFFFF}] {self.operation} '
            f'{_write_fields(self.fields)} (duration: {total_ms:.3f}ms)'
        ]
        prev = self.start_time
        for t, msg, fields in self.steps:
            lines.append(
                f'  step {msg} {_write_fields(fields)}'
                f' (+{(t - prev) * 1e3:.3f}ms)'
            )
            prev = t
        return "\n".join(lines)

    def to_span(self) -> dict:
        """A plain-dict span for exporters (blackbox.to_chrome_trace):
        relative step offsets in seconds, field values coerced to JSON
        primitives so the span survives json.dumps unmodified."""
        def prim(v):
            return v if isinstance(v, (bool, int, float, str,
                                       type(None))) else repr(v)

        return {
            "op": self.operation,
            "start": self.start_time,
            "dur": self.duration(),
            "fields": {f.key: prim(f.value) for f in self.fields},
            "steps": [
                {"ts": t - self.start_time, "msg": msg,
                 "fields": {f.key: prim(f.value) for f in fields}}
                for t, msg, fields in self.steps
            ],
        }

    def log_if_long(self, threshold_s: float = 0.1) -> bool:
        """Log the timeline if total duration exceeded the threshold (the
        warningApplyDuration dump rule, v3_server.go:602-610). Returns
        whether it logged."""
        if self.is_empty or self.duration() < threshold_s:
            return False
        from etcd_tpu.utils.logging import get_logger

        get_logger().warning("%s", self.format())
        return True
