"""raftexample (contrib/raftexample analog): the canonical RawNode-driving
program — elect, replicate, survive drops, restart from storage."""
import pytest

from examples.raftexample import Cluster, RaftExampleNode
from etcd_tpu.types import ROLE_LEADER


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(3)
    assert c.elect(0) == 0
    return c


def test_put_replicates_everywhere(cluster):
    cluster.put("k1", "v1")
    for nid in cluster.nodes:
        assert cluster.get("k1", nid) == "v1"


def test_overwrite(cluster):
    cluster.put("k2", "a")
    cluster.put("k2", "b")
    for nid in cluster.nodes:
        assert cluster.get("k2", nid) == "b"


def test_drop_fault_heals(cluster):
    """Drop all traffic to node 2 during a put; after the link heals the
    leader's retransmission catches it up (transport drop contract)."""
    lead = cluster.leader()
    cluster.network.drop = {(m, 2) for m in cluster.nodes if m != 2}
    cluster.put("k3", "v3")
    assert cluster.get("k3", 2) is None  # isolated
    cluster.network.drop = set()
    # leader needs a nudge to resend: a follow-up put carries commit
    cluster.put("k4", "v4")
    cluster.settle()
    assert cluster.get("k3", 2) == "v3"
    assert cluster.get("k4", 2) == "v4"


def test_restart_from_storage(cluster):
    """A node rebuilt from its MemoryStorage replays committed entries
    into a fresh kv store (the raftexample replayWAL path)."""
    cluster.put("k5", "v5")
    victim = next(n for n in cluster.nodes if n != cluster.leader())
    old = cluster.nodes[victim]
    reborn = RaftExampleNode(cluster.cfg, cluster.spec, victim,
                             cluster.proposals, storage=old.storage)
    # replay: committed entries land in Ready.committed_entries again
    cluster.nodes[victim] = reborn
    cluster.network.nodes = cluster.nodes
    cluster.settle()
    assert reborn.kv.lookup("k5") == "v5"
    assert reborn.kv.lookup("k1") == "v1"


def test_leader_status(cluster):
    lead = cluster.leader()
    st = cluster.nodes[lead].node.status()
    assert st.soft_state.role == ROLE_LEADER
    assert len(st.progress) == 3
