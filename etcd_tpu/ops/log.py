"""Log-ring kernels: term lookup, conflict probes, append, commit cursors.

TPU-native re-expression of the reference's ``raftLog`` (raft/log.go) over a
fixed-capacity ring: entry index ``i`` lives at slot ``(i-1) % L`` and the
valid window is ``(snap_index, last_index]``. The stable/unstable split of
raft/log_unstable.go disappears (pure-device log); ``ErrCompacted`` /
``ErrUnavailable`` become ``ok`` flags.

All functions take/return a single NodeState (vmapped by callers).
"""
from __future__ import annotations

import jax.numpy as jnp

from etcd_tpu.models.state import NodeState
from etcd_tpu.types import Spec


def slot(spec: Spec, idx: jnp.ndarray) -> jnp.ndarray:
    return (idx - 1) % spec.L


def ring_read(ring: jnp.ndarray, s: jnp.ndarray) -> jnp.ndarray:
    """ring[s] without a gather: one-hot mask-and-reduce over the small
    static L axis. Dynamic per-lane indexing lowers to an HLO gather, which
    the TPU executes as a serial scan (measured ~10ms per [M, C] gather at
    C=2k — 1000x the cost of this reduce); with L<=64 the one-hot contraction
    stays in the VPU and fuses with its producers.

    ring: [L]; s: scalar or [...]-shaped indices. Returns s-shaped values.
    """
    L = ring.shape[-1]
    oh = jnp.arange(L, dtype=jnp.int32) == jnp.asarray(s)[..., None]  # [..., L]
    return jnp.where(oh, ring, 0).sum(axis=-1).astype(ring.dtype)


def roll_left(a: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """jnp.roll(a, -k, axis=0) for a traced k without a gather (dynamic
    roll lowers to one — see ring_read): one-hot permutation matrix over
    the small static leading axis, trailing dims carried along."""
    N = a.shape[0]
    offs = jnp.arange(N, dtype=jnp.int32)
    sh = offs[:, None] == ((offs[None, :] + k) % N)  # [src, dst]
    oh = sh.reshape(sh.shape + (1,) * (a.ndim - 1))
    return jnp.where(oh, a[:, None], 0).sum(axis=0).astype(a.dtype)


def first_index(n: NodeState) -> jnp.ndarray:
    return n.snap_index + 1


def term_at(spec: Spec, n: NodeState, idx: jnp.ndarray):
    """(term, ok). Mirrors raftLog.term (log.go:265-285): ok is False outside
    [snap_index, last_index] (the reference returns (0, nil) below the dummy
    index and errors inside the compacted range; callers here only need the
    combined "can't tell" signal)."""
    t = ring_read(n.log_term, slot(spec, idx))
    t = jnp.where(idx == n.snap_index, n.snap_term, t)
    ok = (idx >= n.snap_index) & (idx <= n.last_index)
    return jnp.where(ok, t, 0).astype(jnp.int32), ok


def match_term(spec: Spec, n: NodeState, idx: jnp.ndarray, term: jnp.ndarray):
    t, ok = term_at(spec, n, idx)
    return ok & (t == term)


def last_term(spec: Spec, n: NodeState) -> jnp.ndarray:
    t, _ = term_at(spec, n, n.last_index)
    return t


def is_up_to_date(spec: Spec, n: NodeState, lasti, term) -> jnp.ndarray:
    """raftLog.isUpToDate (log.go:313-315)."""
    lt = last_term(spec, n)
    return (term > lt) | ((term == lt) & (lasti >= n.last_index))


def commit_to(n: NodeState, tocommit: jnp.ndarray) -> NodeState:
    """raftLog.commitTo (log.go:233-241); never decreases, clamped to
    last_index (the reference panics past lastIndex — heartbeats only carry
    min(match, commit) so the clamp is defensive)."""
    c = jnp.clip(tocommit, n.commit, n.last_index)
    return n.replace(commit=jnp.maximum(n.commit, c))


def find_conflict_by_term(spec: Spec, n: NodeState, index, term) -> jnp.ndarray:
    """Largest i <= index with term(i) <= term (raft/log.go:147-168), the
    log-divergence probe optimization. Out-of-range index is returned as-is.

    Masked-max over the ring instead of the reference's walk-down loop; the
    candidates below snap_index all have effective term 0 <= term, so
    min(index, snap_index - 1) is always achievable, exactly like the
    reference's term()==(0, nil) floor."""
    idxs = jnp.arange(spec.L, dtype=jnp.int32)
    # entry index stored in each slot, for the current window
    ent_idx = n.last_index - ((slot(spec, n.last_index) - idxs) % spec.L)
    in_win = (ent_idx > n.snap_index) & (ent_idx <= jnp.minimum(index, n.last_index))
    cand = jnp.where(in_win & (n.log_term <= term), ent_idx, -1)
    best = cand.max()
    best = jnp.maximum(
        best,
        jnp.where((n.snap_term <= term) & (n.snap_index <= index), n.snap_index, -1),
    )
    best = jnp.maximum(best, jnp.minimum(index, n.snap_index - 1))
    return jnp.where(index > n.last_index, index, best).astype(jnp.int32)


def append_span(
    spec: Spec,
    n: NodeState,
    prev_index: jnp.ndarray,
    ent_len: jnp.ndarray,
    ent_term: jnp.ndarray,
    ent_data: jnp.ndarray,
    ent_type: jnp.ndarray,
    enable: jnp.ndarray,
) -> NodeState:
    """Unconditionally truncate-and-append entries (prev_index, prev_index+len]
    when `enable`; callers implement the maybeAppend/findConflict policy.
    After the write last_index = prev_index + ent_len (truncation semantics of
    unstable.truncateAndAppend, log_unstable.go:121)."""
    new_last = prev_index + ent_len
    # all E offered slots written in one one-hot pass (consecutive indexes
    # map to distinct ring slots, so at most one e hits each slot)
    offs = jnp.arange(spec.E, dtype=jnp.int32)
    s = slot(spec, prev_index + 1 + offs)  # [E]
    write = enable & (offs < ent_len)  # [E]
    oh = (jnp.arange(spec.L, dtype=jnp.int32)[None, :] == s[:, None]) & (
        write[:, None]
    )  # [E, L]
    hit = oh.any(axis=0)  # [L]

    def merge(ring, vals):
        new = jnp.where(oh, vals[:, None], 0).sum(axis=0).astype(ring.dtype)
        return jnp.where(hit, new, ring)

    n = n.replace(
        log_term=merge(n.log_term, ent_term),
        log_data=merge(n.log_data, ent_data),
        log_type=merge(n.log_type, ent_type),
    )
    return n.replace(last_index=jnp.where(enable, new_last, n.last_index))


def maybe_append(
    spec: Spec,
    n: NodeState,
    m_index: jnp.ndarray,
    m_log_term: jnp.ndarray,
    m_commit: jnp.ndarray,
    ent_len: jnp.ndarray,
    ent_term: jnp.ndarray,
    ent_data: jnp.ndarray,
    ent_type: jnp.ndarray,
    enable: jnp.ndarray,
):
    """raftLog.maybeAppend (log.go:88-104). Returns (state, last_new_i, ok).

    findConflict (log.go:127-138): first offered entry whose term mismatches
    the local log (an index past last_index always mismatches). Entries before
    the conflict are already present; entries from the conflict on are
    truncate-appended. Conflicts at/below commit panic in the reference; here
    they cannot happen for well-formed inputs and are simply overwritten.
    """
    ok = match_term(spec, n, m_index, m_log_term)
    do = enable & ok
    last_new_i = m_index + ent_len

    # conflict scan over the (small, static) offered span
    offs = jnp.arange(spec.E, dtype=jnp.int32)
    idxs = m_index + 1 + offs
    valid = offs < ent_len
    t_here, ok_here = term_at(spec, n, idxs)
    matches = valid & ok_here & (t_here == ent_term)
    mismatch = valid & ~matches
    any_conflict = mismatch.any()
    ci_off = jnp.where(any_conflict, jnp.argmax(mismatch), 0).astype(jnp.int32)

    # append entries [ci, last_new_i]; shift the offered span left by ci_off
    # so append_span sees prev_index = m_index + ci_off
    def shift(a):
        return roll_left(a, ci_off)

    n = append_span(
        spec,
        n,
        m_index + ci_off,
        ent_len - ci_off,
        shift(ent_term),
        shift(ent_data),
        shift(ent_type),
        do & any_conflict,
    )
    # gated commitTo(min(m_commit, last_new_i))
    c = jnp.clip(jnp.minimum(m_commit, last_new_i), n.commit, n.last_index)
    n = n.replace(commit=jnp.where(do, jnp.maximum(n.commit, c), n.commit))
    return n, jnp.where(do, last_new_i, 0).astype(jnp.int32), ok


def entries_from(spec: Spec, n: NodeState, lo: jnp.ndarray):
    """Up to E entries starting at index `lo` (raftLog.entries / slice used by
    maybeSendAppend, raft.go:441). Returns (len, term[E], data[E], type[E]).
    Caller guarantees lo > snap_index (else the snapshot path is taken)."""
    offs = jnp.arange(spec.E, dtype=jnp.int32)
    idxs = lo + offs
    valid = (idxs >= first_index(n)) & (idxs <= n.last_index)
    s = slot(spec, idxs)
    ln = jnp.clip(n.last_index - lo + 1, 0, spec.E).astype(jnp.int32)
    zero = jnp.zeros((spec.E,), jnp.int32)
    return (
        ln,
        jnp.where(valid, ring_read(n.log_term, s), zero),
        jnp.where(valid, ring_read(n.log_data, s), zero),
        jnp.where(valid, ring_read(n.log_type, s), zero),
    )


def count_pending_conf(spec: Spec, n: NodeState, lo: jnp.ndarray, hi: jnp.ndarray):
    """#conf-change entries with index in (lo, hi] — numOfPendingConf over
    the (applied, committed] window used by hup (raft.go:760-777)."""
    idxs = jnp.arange(spec.L, dtype=jnp.int32)
    ent_idx = n.last_index - ((slot(spec, n.last_index) - idxs) % spec.L)
    in_win = (ent_idx > lo) & (ent_idx <= hi) & (ent_idx > n.snap_index) & (
        ent_idx <= n.last_index
    )
    from etcd_tpu.types import ENTRY_CONF_CHANGE

    return (in_win & (n.log_type == ENTRY_CONF_CHANGE)).sum().astype(jnp.int32)
