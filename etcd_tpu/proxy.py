"""L7 proxy: caching/coalescing front for the v3 API.

The reference's grpcproxy (server/proxy/grpcproxy) multiplexes many
clients onto one upstream connection: serializable Ranges answer from an
invalidated cache (grpcproxy/cache/store.go), watches on the same range
coalesce onto a single upstream watcher that broadcasts events
(watch_broadcast.go), everything else passes through. tcpproxy is the
L4 gateway variant.

This serves the same JSON/HTTP surface as etcd_tpu.server.v3rpc and
forwards to any backing endpoint, adding:
  * a serializable-Range cache keyed by (key, range_end, limit,
    count_only), invalidated on any write that touches the range;
  * watch coalescing: one upstream watch per (key, range_end), events
    fanned out to every attached client watcher;
  * passthrough for all other routes.

Usage:
    python -m etcd_tpu.proxy --endpoint http://127.0.0.1:2379 --port 23790
"""
from __future__ import annotations

import argparse
import base64
import json
import sys
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def _overlaps(akey: bytes, aend: bytes | None, bkey: bytes) -> bool:
    if aend is None:
        return akey == bkey
    if aend == b"\x00":
        return bkey >= akey
    return akey <= bkey < aend


class RangeCache:
    """grpcproxy/cache/store.go: an LRU of serializable Range responses,
    invalidated by overlapping writes."""

    def __init__(self, max_entries: int = 1024):
        self.max = max_entries
        self._data: dict[tuple, dict] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, key: tuple) -> dict | None:
        with self._lock:
            res = self._data.get(key)
            if res is None:
                self.misses += 1
            else:
                self.hits += 1
            return res

    def put(self, key: tuple, value: dict) -> None:
        with self._lock:
            if len(self._data) >= self.max:
                self._data.pop(next(iter(self._data)))
            self._data[key] = value

    def invalidate(self, wkey: bytes, wend: bytes | None = None) -> None:
        with self._lock:
            dead = []
            for entry in self._data:
                ckey, cend = entry[0], entry[1]
                if wend is None:
                    if _overlaps(ckey, cend, wkey):
                        dead.append(entry)
                elif _overlaps(wkey, wend, ckey) or _overlaps(ckey, cend, wkey):
                    dead.append(entry)
            for k in dead:
                del self._data[k]


class WatchCoalescer:
    """watch_broadcast.go: one upstream watcher per range, N subscribers."""

    def __init__(self, call):
        self._call = call
        self._lock = threading.Lock()
        self._bcasts: dict[tuple, dict] = {}  # range -> {upstream, subs}
        self._next_sub = 1

    def create(self, create_request: dict) -> int:
        # coalesce only watches with identical replay semantics: a
        # different start_revision/prev_kv needs its own upstream watcher
        rng = (
            create_request["key"], create_request.get("range_end"),
            int(create_request.get("start_revision", 0) or 0),
            bool(create_request.get("prev_kv")),
        )
        with self._lock:
            b = self._bcasts.get(rng)
            if b is None:
                res = self._call("/v3/watch",
                                 {"create_request": create_request})
                b = {"upstream": int(res["watch_id"]), "subs": {}}
                self._bcasts[rng] = b
            sid = self._next_sub
            self._next_sub += 1
            b["subs"][sid] = []
            return sid

    def poll(self, sub_id: int) -> list[dict]:
        with self._lock:
            for rng, b in self._bcasts.items():
                if sub_id in b["subs"]:
                    res = self._call(
                        "/v3/watch",
                        {"poll_request": {"watch_id": str(b["upstream"])}},
                    )
                    evs = res.get("events", [])
                    if evs:  # broadcast to every subscriber's buffer
                        for q in b["subs"].values():
                            q.extend(evs)
                    out = b["subs"][sub_id]
                    b["subs"][sub_id] = []
                    return out
            return []

    def cancel(self, sub_id: int) -> bool:
        with self._lock:
            for rng, b in list(self._bcasts.items()):
                if sub_id in b["subs"]:
                    del b["subs"][sub_id]
                    if not b["subs"]:  # last subscriber: drop upstream
                        self._call(
                            "/v3/watch",
                            {"cancel_request": {
                                "watch_id": str(b["upstream"])}},
                        )
                        del self._bcasts[rng]
                    return True
        return False


class LeaseCoalescer:
    """Lease keepalive fan-in (grpcproxy/lease.go leaseProxy + clientv3's
    lessor, which multiplexes every local keeper of a lease onto ONE
    upstream keepalive stream): N proxy clients refreshing the same lease
    collapse onto one upstream keepalive per refresh interval. The
    interval follows clientv3's send rule (TTL/3, lease.go keepAliveLoop):
    a keepalive answered within it is served from the cached response
    without touching the upstream."""

    MAX_ENTRIES = 4096  # oldest-entry eviction; naturally-expired leases
    # whose clients just stop calling would otherwise accumulate forever

    def __init__(self, call, clock=None):
        import time

        self._call = call
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._last: dict[int, tuple[float, dict]] = {}  # id -> (t, resp)
        self._forgot: dict[int, float] = {}  # id -> forget() time
        self.upstream_sent = 0
        self.coalesced = 0

    def keepalive(self, q: dict) -> dict:
        lid = int(q.get("ID", 0))
        now = self._clock()
        with self._lock:
            ent = self._last.get(lid)
            if ent is not None:
                t, resp = ent
                ttl = int(resp.get("TTL", 0) or 0)
                if ttl > 0 and (now - t) < ttl / 3.0:
                    self.coalesced += 1
                    return resp
        res = self._call("/v3/lease/keepalive", q)
        with self._lock:
            self.upstream_sent += 1
            # a revoke that raced this upstream call wins: caching the
            # pre-revoke success would serve "alive" for a dead lease
            # until the window lapses
            if self._forgot.pop(lid, -1.0) < now:
                self._last[lid] = (self._clock(), res)
                if len(self._last) > self.MAX_ENTRIES:
                    oldest = min(self._last, key=lambda k: self._last[k][0])
                    del self._last[oldest]
        return res

    def forget(self, lease_id: int) -> None:
        with self._lock:
            self._last.pop(lease_id, None)
            self._forgot[lease_id] = self._clock()
            if len(self._forgot) > self.MAX_ENTRIES:
                oldest = min(self._forgot, key=self._forgot.get)
                del self._forgot[oldest]


class Proxy:
    def __init__(self, endpoint: str):
        self.endpoint = endpoint.rstrip("/")
        self.cache = RangeCache()
        self.watches = WatchCoalescer(self.call)
        self.leases = LeaseCoalescer(self.call)

    def call(self, path: str, body: dict) -> dict:
        req = urllib.request.Request(
            self.endpoint + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req) as resp:
            return json.loads(resp.read())

    def handle(self, path: str, q: dict) -> dict:
        if path == "/v3/kv/range" and q.get("serializable"):
            ck = (
                base64.b64decode(q["key"]),
                base64.b64decode(q["range_end"]) if q.get("range_end")
                else None,
                q.get("limit", 0), bool(q.get("count_only")),
                int(q.get("revision", 0) or 0),  # historical reads are
                # distinct cache entries (grpcproxy cache keys by Revision)
            )
            cached = self.cache.get(ck)
            if cached is not None:
                return cached
            res = self.call(path, q)
            self.cache.put(ck, res)
            return res
        if path in ("/v3/kv/put", "/v3/kv/deleterange"):
            self.cache.invalidate(
                base64.b64decode(q["key"]),
                base64.b64decode(q["range_end"]) if q.get("range_end")
                else None,
            )
            return self.call(path, q)
        if path == "/v3/kv/txn":
            # conservative: any txn invalidates everything it might touch
            for op in q.get("success", []) + q.get("failure", []):
                body = op.get("request_put") or op.get("request_delete_range")
                if body:
                    self.cache.invalidate(
                        base64.b64decode(body["key"]),
                        base64.b64decode(body["range_end"])
                        if body.get("range_end") else None,
                    )
            return self.call(path, q)
        if path == "/v3/lease/keepalive":
            return self.leases.keepalive(q)
        if path == "/v3/lease/revoke":
            # a revoked lease must not serve stale cached keepalives
            self.leases.forget(int(q.get("ID", 0)))
            return self.call(path, q)
        if path == "/v3/watch":
            if "create_request" in q:
                sid = self.watches.create(q["create_request"])
                return {"created": True, "watch_id": str(sid)}
            if "poll_request" in q:
                sid = int(q["poll_request"]["watch_id"])
                return {"watch_id": str(sid),
                        "events": self.watches.poll(sid)}
            if "cancel_request" in q:
                sid = int(q["cancel_request"]["watch_id"])
                return {"canceled": self.watches.cancel(sid),
                        "watch_id": str(sid)}
        return self.call(path, q)


class ProxyServer:
    def __init__(self, endpoint: str, host: str = "127.0.0.1",
                 port: int = 0):
        proxy = Proxy(endpoint)
        self.proxy = proxy

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, code: int, obj: dict) -> None:
                blob = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            def do_GET(self):
                try:
                    with urllib.request.urlopen(
                        proxy.endpoint + self.path
                    ) as r:
                        blob = r.read()
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(blob)))
                    self.end_headers()
                    self.wfile.write(blob)
                except urllib.error.HTTPError as e:
                    self._send(e.code, {"error": str(e)})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", "0"))
                q = json.loads(self.rfile.read(n) or b"{}")
                try:
                    self._send(200, proxy.handle(self.path, q))
                except urllib.error.HTTPError as e:
                    self._send(e.code, json.loads(e.read() or b"{}"))
                except Exception as e:  # pragma: no cover
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]

    def start(self) -> "ProxyServer":
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="etcd-tpu-proxy")
    p.add_argument("--endpoint", default="http://127.0.0.1:2379")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=23790)
    args = p.parse_args(argv)
    srv = ProxyServer(args.endpoint, args.host, args.port).start()
    print(f"proxying :{srv.port} -> {args.endpoint}", file=sys.stderr)
    import signal

    signal.sigwait({signal.SIGINT, signal.SIGTERM})
    srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
