"""Out-of-process e2e tier: spawn ``python -m etcd_tpu.etcdmain`` as a
real subprocess with a data dir, drive it with etcdctl over the real
socket, SIGKILL it, restart it, and assert recovery — the analog of the
reference's e2e framework (tests/e2e/etcd_process.go:35 spawning built
binaries, pkg/expect driving them), collapsed to subprocess + HTTP
readiness polling.

These are the only tests that exercise the CLI entrypoint + data-dir
recovery the way operators use them: as a process with a lifecycle."""
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(data_dir: str, port: int, *extra: str) -> subprocess.Popen:
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=(os.environ.get("XLA_FLAGS", "") +
                   " --xla_force_host_platform_device_count=8").strip(),
    )
    return subprocess.Popen(
        [sys.executable, "-m", "etcd_tpu.etcdmain",
         "--data-dir", data_dir, "--cluster-size", "1",
         "--listen-client-port", str(port), *extra],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def _wait_healthy(url: str, proc: subprocess.Popen, ctx=None,
                  deadline: float = 180.0) -> None:
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline:
        if proc.poll() is not None:
            raise AssertionError(
                f"server process exited early rc={proc.returncode}")
        try:
            with urllib.request.urlopen(url + "/health", timeout=2,
                                        context=ctx) as r:
                if json.loads(r.read()).get("health") == "true":
                    return
        except (urllib.error.URLError, ConnectionError, OSError):
            pass
        time.sleep(0.5)
    raise AssertionError(f"server at {url} never became healthy")


def _stop(proc: subprocess.Popen) -> None:
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=15)


def _ctl(port: int, *argv: str, tls_args: tuple = (),
         scheme: str = "http") -> tuple[int, str]:
    """Run etcdctl in-process against the spawned server (the pkg/expect
    analog: the CLI's real argv surface, exit codes and all)."""
    import contextlib
    import io

    from etcd_tpu import etcdctl

    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = etcdctl.main(["--endpoint", f"{scheme}://127.0.0.1:{port}",
                           *tls_args, *argv])
    return rc, out.getvalue()


@pytest.mark.e2e
def test_e2e_put_get_sigkill_restart(tmp_path):
    """The operator loop: start, write over the wire, kill -9, restart
    from the same data dir, read the data back (the reference's
    etcd_process.go Stop/Restart + datadir recovery loop)."""
    data = str(tmp_path / "d")
    port = _free_port()
    proc = _spawn(data, port)
    url = f"http://127.0.0.1:{port}"
    try:
        _wait_healthy(url, proc)
        rc, _ = _ctl(port, "put", "/e2e/a", "v1")
        assert rc == 0
        rc, _ = _ctl(port, "put", "/e2e/b", "v2")
        assert rc == 0
        rc, out = _ctl(port, "get", "/e2e/a")
        assert rc == 0 and "v1" in out
        # crash hard: no shutdown path runs (SIGKILL)
        proc.kill()
        proc.wait(timeout=15)
    finally:
        _stop(proc)
    port2 = _free_port()
    proc2 = _spawn(data, port2)
    try:
        _wait_healthy(f"http://127.0.0.1:{port2}", proc2)
        rc, out = _ctl(port2, "get", "/e2e/a")
        assert rc == 0 and "v1" in out
        rc, out = _ctl(port2, "get", "/e2e/b")
        assert rc == 0 and "v2" in out
        # and the restarted server still accepts writes
        rc, _ = _ctl(port2, "put", "/e2e/c", "v3")
        assert rc == 0
        rc, out = _ctl(port2, "get", "/e2e/c")
        assert rc == 0 and "v3" in out
    finally:
        _stop(proc2)


@pytest.mark.e2e
def test_e2e_https_auto_tls(tmp_path):
    """--auto-tls end to end: the spawned process generates its own
    certs; etcdctl connects with --cacert; a client without the CA is
    refused at the handshake."""
    pytest.importorskip("cryptography")  # auto-TLS cert generation
    data = str(tmp_path / "d")
    port = _free_port()
    proc = _spawn(data, port, "--auto-tls")
    url = f"https://127.0.0.1:{port}"
    cacert = os.path.join(data, "fixtures", "client", "cert.pem")
    try:
        import ssl

        # build the CA context inside the retry loop: cert.pem may
        # exist but still be mid-write by the subprocess
        ctx = None
        t0 = time.monotonic()
        while time.monotonic() - t0 < 180:
            assert proc.poll() is None, "server exited early"
            try:
                ctx = ssl.create_default_context(cafile=cacert)
                break
            except (OSError, ssl.SSLError):
                time.sleep(0.5)
        assert ctx is not None, "auto-tls cert never became loadable"
        _wait_healthy(url, proc, ctx=ctx)
        tls = ("--cacert", cacert)
        rc, _ = _ctl(port, "put", "/sec/a", "tls-v", tls_args=tls,
                     scheme="https")
        assert rc == 0
        rc, out = _ctl(port, "get", "/sec/a", tls_args=tls,
                       scheme="https")
        assert rc == 0 and "tls-v" in out
        # no CA ⇒ handshake refused, not silently insecure
        with pytest.raises(urllib.error.URLError):
            urllib.request.urlopen(url + "/health", timeout=5)
    finally:
        _stop(proc)


@pytest.mark.e2e
def test_e2e_watch_over_wire(tmp_path):
    """A watch created over the socket sees a put made by a second
    client process-boundary away."""
    from etcd_tpu.client import RemoteClient

    data = str(tmp_path / "d")
    port = _free_port()
    proc = _spawn(data, port)
    url = f"http://127.0.0.1:{port}"
    try:
        _wait_healthy(url, proc)
        watcher = RemoteClient(url)
        w = watcher.watch(b"/ww/", prefix=True)
        rc, _ = _ctl(port, "put", "/ww/k", "seen")
        assert rc == 0
        evs = []
        t0 = time.monotonic()
        while not evs and time.monotonic() - t0 < 30:
            evs = w.events()
            if not evs:
                time.sleep(0.3)
        assert evs and evs[0][0] == "PUT" and evs[0][1] == b"/ww/k"
        assert evs[0][2] == b"seen"
        assert w.cancel()
    finally:
        _stop(proc)


@pytest.mark.e2e
def test_e2e_mtls_cert_cn_auth_survives_restart(tmp_path):
    """The full security stack through real processes: spawn etcdmain
    with explicit TLS flags (CA-signed server cert + required client
    certs), enable auth and scope a user over the wire, authenticate
    by client-cert CN alone, SIGKILL, restart — the auth state and TLS
    config must survive the data dir round-trip."""
    pytest.importorskip("cryptography")  # CA + cert issuance
    from etcd_tpu.client import RemoteClient, RemoteError
    from etcd_tpu.transport import TLSInfo, generate_ca, issue_cert

    certs = str(tmp_path / "certs")
    ca = generate_ca(certs)
    server = issue_cert(certs, ca, "server",
                        hosts=["127.0.0.1", "localhost"])
    alice = issue_cert(certs, ca, "alice")
    data = str(tmp_path / "d")
    port = _free_port()
    tls_flags = ("--cert-file", server.cert_file,
                 "--key-file", server.key_file,
                 "--trusted-ca-file", ca.cert_file,
                 "--client-cert-auth")
    proc = _spawn(data, port, *tls_flags)
    url = f"https://127.0.0.1:{port}"
    alice_tls = TLSInfo(trusted_ca_file=ca.cert_file,
                        client_cert_file=alice.cert_file,
                        client_key_file=alice.key_file)
    try:
        _wait_healthy(url, proc, ctx=alice_tls.client_context())
        from conftest import bootstrap_cert_cn_auth

        cli = RemoteClient(url, tls=alice_tls)
        bootstrap_cert_cn_auth(cli.call)
        # cert-CN identity: no token, scoped to /app/*
        cli.put(b"/app/sec", b"by-cert")
        with pytest.raises(RemoteError):
            cli.put(b"/outside", b"nope")
        proc.kill()
        proc.wait(timeout=15)
    finally:
        _stop(proc)
    port2 = _free_port()
    proc2 = _spawn(data, port2, *tls_flags)
    url2 = f"https://127.0.0.1:{port2}"
    try:
        _wait_healthy(url2, proc2, ctx=alice_tls.client_context())
        cli2 = RemoteClient(url2, tls=alice_tls)
        # auth survived: still enabled, alice still scoped, data intact
        assert cli2.get(b"/app/sec") == b"by-cert"
        with pytest.raises(RemoteError):
            cli2.put(b"/outside", b"still-denied")
        cli2.put(b"/app/after", b"post-restart")
        assert cli2.get(b"/app/after") == b"post-restart"
    finally:
        _stop(proc2)
