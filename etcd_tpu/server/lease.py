"""Lease subsystem — TTL'd handles that expire keys.

Mirrors ``server/lease/lessor.go``: `Lessor` owns a min-heap expiry queue
(LeaseExpiredNotifier), leases attach key sets, only the *primary* lessor
(on the raft leader; Promote/Demote at leadership change, lessor.go:81-89)
expires; remaining-TTL checkpoints flow through consensus so a new leader
doesn't reset clocks (leasepb checkpoint, lessor.go Checkpoint). Time is a
logical tick counter fed by the server's round clock — deterministic, like
everything in the batched engine.
"""
from __future__ import annotations

import dataclasses
import heapq


class LeaseError(Exception):
    pass


class ErrLeaseNotFound(LeaseError):
    pass


class ErrLeaseExists(LeaseError):
    pass


@dataclasses.dataclass
class Lease:
    id: int
    ttl: int                 # granted TTL in ticks
    expiry: int              # absolute tick of expiry (primary only)
    keys: set[bytes] = dataclasses.field(default_factory=set)
    remaining_checkpoint: int | None = None  # persisted remaining TTL


class Lessor:
    MIN_TTL = 1

    def __init__(self, min_ttl: int = 1):
        self.leases: dict[int, Lease] = {}
        self.item_map: dict[bytes, int] = {}  # key -> lease id
        self.min_ttl = min_ttl
        self.primary = False
        self.now = 0
        self._heap: list[tuple[int, int]] = []  # (expiry, id)

    # -- clock --------------------------------------------------------------
    def tick(self, n: int = 1) -> None:
        self.now += n

    # -- grant/revoke (lessor.go Grant/Revoke) -------------------------------
    def grant(self, lease_id: int, ttl: int) -> Lease:
        if lease_id <= 0:
            raise LeaseError("invalid lease id")
        if lease_id in self.leases:
            raise ErrLeaseExists(lease_id)
        ttl = max(ttl, self.min_ttl)
        l = Lease(lease_id, ttl, self.now + ttl)
        self.leases[lease_id] = l
        if self.primary:
            heapq.heappush(self._heap, (l.expiry, lease_id))
        return l

    def revoke(self, lease_id: int) -> list[bytes]:
        """Returns the attached keys (the server deletes them through an
        applied RaftRequest, lessor.go revokes via RevokeLease txn)."""
        l = self.leases.pop(lease_id, None)
        if l is None:
            raise ErrLeaseNotFound(lease_id)
        keys = sorted(l.keys)
        for k in keys:
            self.item_map.pop(k, None)
        return keys

    def renew(self, lease_id: int) -> int:
        """KeepAlive: reset expiry to now+TTL; primary-only (lessor.go)."""
        l = self.leases.get(lease_id)
        if l is None:
            raise ErrLeaseNotFound(lease_id)
        l.remaining_checkpoint = None
        l.expiry = self.now + l.ttl
        if self.primary:
            heapq.heappush(self._heap, (l.expiry, lease_id))
        return l.ttl

    def time_to_live(self, lease_id: int) -> tuple[int, list[bytes]]:
        l = self.leases.get(lease_id)
        if l is None:
            raise ErrLeaseNotFound(lease_id)
        remaining = max(l.expiry - self.now, 0) if self.primary else l.ttl
        return remaining, sorted(l.keys)

    # -- key attachment (lessor.go Attach/Detach via mvcc put) ---------------
    def attach(self, lease_id: int, key: bytes) -> None:
        l = self.leases.get(lease_id)
        if l is None:
            raise ErrLeaseNotFound(lease_id)
        old = self.item_map.get(key)
        if old is not None and old != lease_id and old in self.leases:
            self.leases[old].keys.discard(key)
        l.keys.add(key)
        self.item_map[key] = lease_id

    def detach(self, key: bytes) -> None:
        lid = self.item_map.pop(key, None)
        if lid is not None and lid in self.leases:
            self.leases[lid].keys.discard(key)

    def lease_of(self, key: bytes) -> int:
        return self.item_map.get(key, 0)

    # -- leadership (lessor.go Promote/Demote) -------------------------------
    def promote(self, extend: int = 0) -> None:
        """New leader: refresh every expiry from its TTL (the reference
        extends by the election timeout so in-flight keepalives survive)."""
        self.primary = True
        self._heap = []
        for l in self.leases.values():
            if l.remaining_checkpoint is not None:
                l.expiry = self.now + l.remaining_checkpoint
            else:
                l.expiry = self.now + l.ttl + extend
            heapq.heappush(self._heap, (l.expiry, l.id))

    def demote(self) -> None:
        self.primary = False
        self._heap = []

    # -- checkpointing (lessor.go Checkpoint; flows through raft) ------------
    def checkpoint(self) -> list[tuple[int, int]]:
        """[(lease_id, remaining_ttl)] for the leader to replicate."""
        if not self.primary:
            return []
        return [
            (l.id, max(l.expiry - self.now, 0)) for l in self.leases.values()
        ]

    def apply_checkpoint(self, lease_id: int, remaining: int) -> None:
        l = self.leases.get(lease_id)
        if l is not None:
            l.remaining_checkpoint = remaining

    # -- snapshot/restore (leaseBucket persistence, schema/lease.go) ---------
    def to_snapshot(self) -> dict:
        """(ttl, remaining, keys) per lease; remaining is measured from the
        snapshot moment so the restored member's local clock origin doesn't
        matter (the reference persists ID+TTL and checkpoints remaining)."""
        return {
            l.id: {
                "ttl": l.ttl,
                "remaining": (
                    max(l.expiry - self.now, 0)
                    if self.primary
                    else l.remaining_checkpoint
                ),
                "keys": sorted(l.keys),
            }
            for l in self.leases.values()
        }

    def restore(self, snap: dict) -> None:
        self.leases = {}
        self.item_map = {}
        self.primary = False
        self._heap = []
        for lid, d in snap.items():
            l = Lease(lid, d["ttl"], self.now + (d["remaining"] or d["ttl"]),
                      set(d["keys"]), d["remaining"])
            self.leases[lid] = l
            for k in l.keys:
                self.item_map[k] = lid

    # -- expiry (lessor.go expireExists / runLoop) ---------------------------
    def expired(self, limit: int = 16) -> list[int]:
        """Lease ids due at the current tick (primary only). The server
        turns each into a LeaseRevoke proposal through consensus."""
        if not self.primary:
            return []
        out = []
        while self._heap and len(out) < limit:
            exp, lid = self._heap[0]
            l = self.leases.get(lid)
            if l is None:
                heapq.heappop(self._heap)
                continue
            if l.expiry != exp:  # stale heap entry after renew
                heapq.heappop(self._heap)
                continue
            if exp > self.now:
                break
            heapq.heappop(self._heap)
            out.append(lid)
        return out

    def defer_expiry(self, lease_ids) -> None:
        """Re-queue ids whose revoke proposal failed so they retry next tick
        (expired() already popped their heap entries; without this they
        would never expire again)."""
        if not self.primary:
            return
        for lid in lease_ids:
            l = self.leases.get(lid)
            if l is not None:
                heapq.heappush(self._heap, (l.expiry, lid))
