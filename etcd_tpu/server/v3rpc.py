"""Network façade: the v3 API served over JSON/HTTP.

The reference serves clients gRPC (server/etcdserver/api/v3rpc/grpc.go:39)
plus a JSON/HTTP mapping of the exact same services via the gRPC gateway
(api/etcdserverpb/rpc.proto's google.api.http annotations: /v3/kv/range,
/v3/kv/put, /v3/lease/grant, ...), and a plain-HTTP sidecar for
/health, /version and /metrics (api/etcdhttp). The TPU build serves the
gateway mapping directly — same paths, same JSON field conventions
(bytes base64-encoded, int64s as strings accepted) — over a threaded
stdlib HTTP server; one process-wide lock serializes access to the
EtcdCluster, mirroring the reference's single apply loop.

Streams: gRPC's bidi Watch/LeaseKeepAlive become create/poll/cancel
POSTs (a long-poll gateway, the same shape the reference's gateway
emulates with chunked JSON frames).

Election/Lock: the v3election/v3lock services
(server/etcdserver/api/v3election, v3lock) are served on their gateway
paths, implemented over the same concurrency recipes the client library
uses, bound to the caller's lease.
"""
from __future__ import annotations

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from etcd_tpu.client import Client, prefix_range_end
from etcd_tpu.concurrency import Election, Mutex, Session
from etcd_tpu.server.kvserver import Compare, EtcdCluster, Op, ServerError

from etcd_tpu.server.version import MIN_CLUSTER_VERSION, SERVER_VERSION
from etcd_tpu.utils.trace import Field, Trace

__version__ = SERVER_VERSION


def _b64(b: bytes | None) -> str | None:
    return base64.b64encode(b).decode() if b is not None else None


def _unb64(s: str | None) -> bytes | None:
    return base64.b64decode(s) if s is not None else None


def _int(v, default=0) -> int:
    if v is None:
        return default
    return int(v)  # the gateway accepts int64 as JSON string


def _kv_json(kv) -> dict:
    return {
        "key": _b64(kv.key),
        "value": _b64(kv.value),
        "create_revision": str(kv.create_revision),
        "mod_revision": str(kv.mod_revision),
        "version": str(kv.version),
        "lease": str(kv.lease),
    }


def _header_json(h) -> dict:
    return {
        "cluster_id": "1", "member_id": str(h.member_id),
        "revision": str(h.revision), "raft_term": str(h.raft_term),
    }


class _BoundSession(Session):
    """A Session over a caller-provided lease (v3election campaign takes
    the lease id on the wire, v3election/v3electionpb)."""

    def __init__(self, client: Client, lease_id: int):
        self.client = client
        self.lease_id = lease_id


class V3Api:
    """Request-level service implementation, transport-free (the analog
    of the v3rpc service structs); V3Server wires it to HTTP."""

    def __init__(self, ec: EtcdCluster):
        self.ec = ec
        self.lock = threading.RLock()
        self._watch_member = 0

    # -- kv ------------------------------------------------------------------
    # every KV handler opens the request's Trace HERE — the earliest
    # host-side point, so the recorded span covers json-decode-to-respond
    # (the reference starts its traceutil.Trace at the grpc handler,
    # v3_server.go:95-133); kvserver threads it through propose ->
    # wait-applied -> respond and retires it into ec.req_spans for
    # blackbox.to_chrome_trace
    def kv_range(self, q: dict) -> dict:
        trace = Trace("range", Field("rpc", "kv_range"))
        kvs = self.ec.range(
            _unb64(q["key"]),
            _unb64(q.get("range_end")),
            rev=_int(q.get("revision")),
            limit=_int(q.get("limit")),
            serializable=bool(q.get("serializable")),
            count_only=bool(q.get("count_only")),
            token=q.get("_token"),
            trace=trace,
        )
        return {
            "header": _header_json(kvs["header"]),
            "kvs": [_kv_json(kv) for kv in kvs["kvs"]],
            "count": str(kvs["count"]),
        }

    def _header(self) -> dict:
        return _header_json(self.ec._header(self.ec.ensure_leader()))

    def kv_put(self, q: dict) -> dict:
        trace = Trace("put", Field("rpc", "kv_put"))
        res = self.ec.put(
            _unb64(q["key"]), _unb64(q.get("value")) or b"",
            lease=_int(q.get("lease")),
            prev_kv=bool(q.get("prev_kv")),
            token=q.get("_token"),
            trace=trace,
        )
        out = {"header": self._header()}
        if res.get("prev_kv"):
            out["prev_kv"] = _kv_json(res["prev_kv"])
        return out

    def kv_deleterange(self, q: dict) -> dict:
        trace = Trace("delete_range", Field("rpc", "kv_deleterange"))
        res = self.ec.delete_range(
            _unb64(q["key"]), _unb64(q.get("range_end")),
            prev_kv=bool(q.get("prev_kv")),
            token=q.get("_token"),
            trace=trace,
        )
        out = {
            "header": self._header(),
            "deleted": str(res["deleted"]),
        }
        if res.get("prev_kvs"):
            out["prev_kvs"] = [_kv_json(kv) for kv in res["prev_kvs"]]
        return out

    def _parse_op(self, j: dict) -> Op:
        if "request_put" in j:
            p = j["request_put"]
            return Op("put", _unb64(p["key"]), _unb64(p.get("value")) or b"",
                      lease=_int(p.get("lease")))
        if "request_delete_range" in j:
            p = j["request_delete_range"]
            return Op("delete", _unb64(p["key"]), range_end=_unb64(p.get("range_end")))
        if "request_range" in j:
            p = j["request_range"]
            return Op("range", _unb64(p["key"]),
                      range_end=_unb64(p.get("range_end")),
                      rev=_int(p.get("revision")), limit=_int(p.get("limit")))
        raise ServerError("unsupported txn op")

    def _parse_cmp(self, j: dict) -> Compare:
        target = j.get("target", "VALUE").lower()
        result = {"EQUAL": "=", "GREATER": ">", "LESS": "<",
                  "NOT_EQUAL": "!="}[j.get("result", "EQUAL")]
        key = _unb64(j["key"])
        if target == "value":
            return Compare(key, "value", result, _unb64(j.get("value")) or b"")
        field = {"version": "version", "create": "create", "mod": "mod",
                 "lease": "lease"}[target]
        val = _int(j.get(field if field != "create" else "create_revision",
                         j.get(field + "_revision", j.get(field))))
        return Compare(key, field, result, val)

    def kv_txn(self, q: dict) -> dict:
        trace = Trace("txn", Field("rpc", "kv_txn"))
        res = self.ec.txn(
            [self._parse_cmp(c) for c in q.get("compare", [])],
            [self._parse_op(o) for o in q.get("success", [])],
            [self._parse_op(o) for o in q.get("failure", [])],
            token=q.get("_token"),
            trace=trace,
        )
        responses = []
        for entry in res["responses"]:
            kind = entry[0]
            if kind == "put":
                responses.append({"response_put": {"header": {}}})
            elif kind == "delete":
                responses.append(
                    {"response_delete_range": {"deleted": str(entry[1])}}
                )
            else:  # ("range", kvs, count) — a 3-tuple, unlike the others
                responses.append({
                    "response_range": {
                        "kvs": [_kv_json(kv) for kv in entry[1]],
                        "count": str(entry[2]),
                    }
                })
        return {
            "header": self._header(),
            "succeeded": res["succeeded"],
            "responses": responses,
        }

    def kv_compaction(self, q: dict) -> dict:
        self.ec.compact(_int(q.get("revision")))
        return {"header": {}}

    # -- watch (create/poll/cancel/progress long-poll mapping) ---------------
    # fragment budget: the reference splits WatchResponses at the stream's
    # maxRequestBytes (1.5 MiB default, sendFragments at
    # api/v3rpc/watch.go:508-545); here the budget bounds the JSON body
    MAX_WATCH_RESPONSE_BYTES = 3 << 20

    def watch(self, q: dict) -> dict:
        if "create_request" in q:
            c = q["create_request"]
            known = {"NOPUT": "put", "NODELETE": "delete"}
            bad = [f for f in c.get("filters", []) if f not in known]
            if bad:
                raise ServerError(f"unknown watch filters {bad}")
            filters = tuple(known[f] for f in c.get("filters", []))
            trace = Trace("watch_create", Field("rpc", "watch"))
            w = self.ec.watch(
                self._watch_member,
                _unb64(c["key"]), _unb64(c.get("range_end")),
                start_rev=_int(c.get("start_revision")),
                prev_kv=bool(c.get("prev_kv")),
                fragment=bool(c.get("fragment")),
                progress_notify=bool(c.get("progress_notify")),
                filters=filters,
            )
            trace.step("watcher registered", Field("watch_id", w.id))
            trace.log_if_long(self.ec.TRACE_THRESHOLD_S)
            self.ec._record_span(trace)
            return {"created": True, "watch_id": str(w.id)}
        if "poll_request" in q:
            return self._watch_poll(q["poll_request"])
        if "progress_request" in q:
            # WatchProgressRequest (watch.go:339-345): a bare revision
            # header, watch_id -1, "broadcast" to the stream
            rev = self.ec.watch_progress(self._watch_member)
            return {"watch_id": "-1",
                    "header": {"revision": str(rev)}}
        if "cancel_request" in q:
            wid = _int(q["cancel_request"]["watch_id"])
            return {"canceled": self.ec.cancel_watch(self._watch_member, wid),
                    "watch_id": str(wid)}
        raise ServerError("watch: need create/poll/cancel/progress request")

    def _watch_poll(self, p: dict) -> dict:
        m = self._watch_member
        wid = _int(p["watch_id"])
        budget = _int(p.get("max_response_bytes")) or \
            self.MAX_WATCH_RESPONSE_BYTES
        store = self.ec.members[m].store
        watcher = store.get_watcher(wid)
        frag_on = watcher is not None and watcher.fragment
        store.sync_watchers()  # one catch-up pass for this poll
        events, size = [], 0
        while True:
            batch = store.take_events(wid, limit=1 if frag_on else None)
            if not batch:
                break
            for e in batch:
                ej = {
                    "type": "PUT" if e.type == "put" else "DELETE",
                    "kv": _kv_json(e.kv),
                    **({"prev_kv": _kv_json(e.prev_kv)} if e.prev_kv else {}),
                }
                events.append(ej)
                size += len(json.dumps(ej))
            if not frag_on or size >= budget:
                break
        more = self.ec.watch_pending(m, wid) > 0
        resp = {
            "watch_id": str(wid),
            "header": {
                "revision": str(self.ec.members[m].store.kv.current_rev)
            },
            "events": events,
        }
        if frag_on and more:
            # sendFragments: every response but the last is marked
            resp["fragment"] = True
        if (not events and not more and watcher is not None
                and watcher.progress_notify):
            # idle progress notification (WatchResponse with no events and
            # a current revision header, watch.go progress path)
            rev = self.ec.watch_progress(m, wid)
            if rev is not None:
                resp["progress_notify"] = True
        return resp

    # -- lease ---------------------------------------------------------------
    def lease_grant(self, q: dict) -> dict:
        res = self.ec.lease_grant(_int(q.get("ID")), _int(q.get("TTL")))
        return {"ID": str(res["id"]), "TTL": str(res["ttl"]), "header": {}}

    def lease_revoke(self, q: dict) -> dict:
        self.ec.lease_revoke(_int(q.get("ID")))
        return {"header": {}}

    def lease_keepalive(self, q: dict) -> dict:
        res = self.ec.lease_keepalive(_int(q.get("ID")))
        return {"ID": str(res["id"]), "TTL": str(res["ttl"]), "header": {}}

    def lease_timetolive(self, q: dict) -> dict:
        res = self.ec.lease_time_to_live(_int(q.get("ID")))
        out = {"ID": str(res["id"]), "TTL": str(res["ttl"]),
               "grantedTTL": str(res.get("granted_ttl", res["ttl"])),
               "header": {}}
        if q.get("keys"):
            out["keys"] = [_b64(k) for k in res.get("keys", [])]
        return out

    def lease_leases(self, q: dict) -> dict:
        return {"leases": [{"ID": str(i)} for i in self.ec.leases()],
                "header": {}}

    # -- cluster -------------------------------------------------------------
    def member_add(self, q: dict) -> dict:
        mid = _int(q.get("ID"))
        self.ec.member_add(mid, learner=bool(q.get("is_learner")))
        return {"header": {}, "member": {"ID": str(mid),
                                         "is_learner": bool(q.get("is_learner"))}}

    def member_remove(self, q: dict) -> dict:
        self.ec.member_remove(_int(q.get("ID")))
        return {"header": {}}

    def member_promote(self, q: dict) -> dict:
        self.ec.member_promote(_int(q.get("ID")))
        return {"header": {}}

    def member_list(self, q: dict) -> dict:
        cfg = self.ec.member_config()
        return {
            "header": {},
            "members": [
                {"ID": str(i), "is_learner": i in cfg.learners}
                for i in sorted(cfg.progress)
            ],
        }

    # -- maintenance ---------------------------------------------------------
    def maintenance_status(self, q: dict) -> dict:
        st = self.ec.status(q.get("_member", self.ec.ensure_leader()))
        return {**{k: (str(v) if isinstance(v, int) else v)
                   for k, v in st.items()}, "version": __version__}

    def maintenance_hash_kv(self, q: dict) -> dict:
        m = q.get("_member", self.ec.ensure_leader())
        return {"hash": str(self.ec.hash_kv(m, _int(q.get("revision")))),
                "header": {}}

    def maintenance_alarm(self, q: dict) -> dict:
        action = q.get("action", "GET")
        if action == "GET":  # reads don't go through consensus
            lead = self.ec.ensure_leader()
            alarms = sorted(self.ec.members[lead].alarms)
        else:
            alarms = self.ec.alarm(
                {"ACTIVATE": "activate", "DEACTIVATE": "deactivate"}[action],
                q.get("alarm", "NOSPACE"),
            )
        return {"header": {}, "alarms": [{"alarm": a} for a in alarms]}

    def maintenance_snapshot(self, q: dict) -> dict:
        m = q.get("_member", self.ec.ensure_leader())
        snap = self.ec.member_snapshot(m)
        # the reference streams the raw backend file (maintenance.go
        # Snapshot); our binary-exact equivalent is the pickled member
        # snapshot — lossless, so `etcdutl snapshot restore` can rebuild
        # a data dir from the saved file
        import pickle

        return {"blob": _b64(pickle.dumps(snap, protocol=4))}

    def maintenance_defragment(self, q: dict) -> dict:
        for ms in self.ec.members:
            if ms.backend is not None:
                ms.backend.defrag()
        return {"header": {}}

    def maintenance_downgrade(self, q: dict) -> dict:
        """DowngradeRequest VALIDATE/ENABLE/CANCEL
        (rpc.proto Maintenance.Downgrade; v3_server.go:901)."""
        a = q.get("action", 0)
        if isinstance(a, str):
            a = {"VALIDATE": 0, "ENABLE": 1, "CANCEL": 2}.get(a.upper(), a)
        action = {0: "validate", 1: "enable", 2: "cancel"}[int(a)]
        res = self.ec.downgrade(action, q.get("version"))
        return {"header": {}, "version": res["version"]}

    # -- auth ----------------------------------------------------------------
    # gateway path suffix -> replicated auth request kind
    AUTH_OPS = {
        "enable": "auth_enable",
        "disable": "auth_disable",
        "user_add": "auth_user_add",
        "user_delete": "auth_user_delete",
        "user_changepw": "auth_user_change_password",
        "user_grant": "auth_user_grant_role",
        "user_revoke": "auth_user_revoke_role",
        "role_add": "auth_role_add",
        "role_delete": "auth_role_delete",
        "role_grant": "auth_role_grant_permission",
        "role_revoke": "auth_role_revoke_permission",
    }

    def auth(self, suffix: str, q: dict) -> dict:
        tok = q.pop("_token", None)
        if suffix == "authenticate":
            out = self.ec.authenticate(q["name"], q["password"])
            return {"token": out, "header": {}}
        kind = self.AUTH_OPS.get(suffix)
        if kind is None:
            raise ServerError(f"unknown auth op {suffix}")
        # AdminPermission (server/etcdserver/v3_server.go AuthInfoFromCtx
        # + auth store's root-role requirement): once auth is on, every
        # admin op needs the root role — via password token or cert-CN
        # identity. Without this the whole auth layer is one
        # /v3/auth/disable away from moot.
        lead = self.ec.ensure_leader()
        a = self.ec.members[lead].auth
        if a.enabled:
            if tok is None:
                raise ServerError(
                    "auth admin: token or cert identity required")
            a.is_admin(tok)
        kw = {k: v for k, v in q.items()}
        if kind == "auth_role_grant_permission":
            from etcd_tpu.server.auth import Permission

            p = kw.pop("perm")
            ptype = {"READ": 0, "WRITE": 1, "READWRITE": 2}[
                p.get("permType", "READWRITE")
            ]
            kw["role"] = kw.pop("name", kw.get("role"))
            kw["perm"] = Permission(
                ptype, _unb64(p["key"]), _unb64(p.get("range_end"))
            )
        if "key" in kw and isinstance(kw["key"], str):
            kw["key"] = _unb64(kw["key"])
        if "range_end" in kw and isinstance(kw["range_end"], str):
            kw["range_end"] = _unb64(kw["range_end"])
        res = self.ec.auth_request(kind, **kw)
        return {"header": {}, "result": _jsonable(res)}

    # -- election / lock (api/v3election, api/v3lock) ------------------------
    def _session(self, lease: int, required: bool = True) -> Session:
        # a shared lease-0 session would collide every caller onto one
        # ownership key and break mutual exclusion
        if required and lease <= 0:
            raise ServerError("a positive lease is required")
        return _BoundSession(Client(self.ec), lease)

    def election_campaign(self, q: dict) -> dict:
        name = _unb64(q["name"])
        e = Election(self._session(_int(q.get("lease"))), name)
        e.campaign(_unb64(q.get("value")) or b"")
        return {
            "header": {},
            "leader": {"name": _b64(name), "key": _b64(e.my_key),
                       "rev": str(e.my_rev), "lease": q.get("lease")},
        }

    def election_proclaim(self, q: dict) -> dict:
        l = q["leader"]
        e = Election(self._session(_int(l.get("lease"))),
                     _unb64(l["name"]))
        e.my_key, e.my_rev = _unb64(l["key"]), _int(l.get("rev"))
        e.proclaim(_unb64(q.get("value")) or b"")
        return {"header": {}}

    def election_leader(self, q: dict) -> dict:
        e = Election(self._session(0, required=False), _unb64(q["name"]))
        kv = e.leader()
        if kv is None:
            raise ServerError("election: no leader")
        return {"header": {}, "kv": _kv_json(kv)}

    def election_resign(self, q: dict) -> dict:
        l = q["leader"]
        e = Election(self._session(_int(l.get("lease"))), _unb64(l["name"]))
        e.my_key, e.my_rev = _unb64(l["key"]), _int(l.get("rev"))
        e.resign()
        return {"header": {}}

    def lock_lock(self, q: dict) -> dict:
        m = Mutex(self._session(_int(q.get("lease"))), _unb64(q["name"]))
        m.lock()
        return {"header": {}, "key": _b64(m.my_key)}

    def lock_unlock(self, q: dict) -> dict:
        self.ec.delete_range(_unb64(q["key"]))
        return {"header": {}}


def _jsonable(x):
    if isinstance(x, bytes):
        return _b64(x)
    if isinstance(x, dict):
        return {str(_jsonable(k)): _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple, set)):
        return [_jsonable(v) for v in sorted(x) if not isinstance(x, (list, tuple))] \
            if isinstance(x, set) else [_jsonable(v) for v in x]
    if hasattr(x, "__dict__"):
        return _jsonable(vars(x))
    return x


ROUTES = {
    "/v3/kv/range": "kv_range",
    "/v3/kv/put": "kv_put",
    "/v3/kv/deleterange": "kv_deleterange",
    "/v3/kv/txn": "kv_txn",
    "/v3/kv/compaction": "kv_compaction",
    "/v3/watch": "watch",
    "/v3/lease/grant": "lease_grant",
    "/v3/lease/revoke": "lease_revoke",
    "/v3/lease/keepalive": "lease_keepalive",
    "/v3/lease/timetolive": "lease_timetolive",
    "/v3/lease/leases": "lease_leases",
    "/v3/cluster/member/add": "member_add",
    "/v3/cluster/member/remove": "member_remove",
    "/v3/cluster/member/promote": "member_promote",
    "/v3/cluster/member/list": "member_list",
    "/v3/maintenance/status": "maintenance_status",
    "/v3/maintenance/hash": "maintenance_hash_kv",
    "/v3/maintenance/alarm": "maintenance_alarm",
    "/v3/maintenance/snapshot": "maintenance_snapshot",
    "/v3/maintenance/defragment": "maintenance_defragment",
    "/v3/maintenance/downgrade": "maintenance_downgrade",
    "/v3/election/campaign": "election_campaign",
    "/v3/election/proclaim": "election_proclaim",
    "/v3/election/leader": "election_leader",
    "/v3/election/resign": "election_resign",
    "/v3/lock/lock": "lock_lock",
    "/v3/lock/unlock": "lock_unlock",
}


class _QuietServer(ThreadingHTTPServer):
    """Failed TLS handshakes and client disconnects are the client's
    story, not server stderr noise; anything else (fd exhaustion, disk
    full, bugs) still gets the default traceback."""

    def handle_error(self, request, client_address):
        import errno
        import ssl
        import sys

        exc = sys.exc_info()[1]
        if isinstance(exc, (ssl.SSLError, ConnectionError,
                            TimeoutError)):
            return
        if isinstance(exc, OSError) and exc.errno in (
                errno.ECONNRESET, errno.EPIPE, errno.ETIMEDOUT,
                errno.ECONNABORTED):
            return
        super().handle_error(request, client_address)


class V3Server:
    """HTTP transport wrapper around V3Api + the etcdhttp endpoints.

    With `tls_info` the listener speaks HTTPS (the NewTLSListener path,
    client/pkg/transport/listener_tls.go): optional required-client-cert
    verification against the trusted CA, the post-handshake
    allowed-CN/hostname gate, and — when client certs are verified —
    the peer CN as a request identity (AuthInfoFromTLS,
    server/auth/store.go:985: the CN is the username, no password)."""

    def __init__(self, ec: EtcdCluster, host: str = "127.0.0.1",
                 port: int = 0, tls_info=None):
        from etcd_tpu.server.v2http import KEYS_PREFIX, V2Api

        self.api = V3Api(ec)
        api = self.api
        self.v2api = V2Api(ec)
        v2api = self.v2api
        if tls_info is not None and tls_info.empty():
            # a half-configured TLSInfo must fail startup, never
            # silently downgrade to plaintext (listener.go:345)
            raise ValueError(
                "KeyFile and CertFile must both be present in tls_info")
        tls = tls_info
        self.tls_info = tls
        self.scheme = "https" if tls else "http"

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            # TLS handshakes run HERE, per-connection in the handler
            # thread (wrap_socket defers them) — a client that connects
            # and sends nothing must never stall the accept loop
            HANDSHAKE_TIMEOUT = 30.0

            def setup(self):
                if tls is not None and hasattr(self.request,
                                               "do_handshake"):
                    self.request.settimeout(self.HANDSHAKE_TIMEOUT)
                    self.request.do_handshake()  # raises -> conn dropped
                    self.request.settimeout(None)
                super().setup()

            def log_message(self, *a):  # quiet
                pass

            def _tls_gate(self) -> bool:
                """allowed-CN / allowed-hostname constraint
                (listener_tls.go:43): False ⇒ request rejected."""
                if tls is None or (not tls.allowed_cn and
                                   not tls.allowed_hostname):
                    return True
                from etcd_tpu.transport import check_cert_constraints

                if check_cert_constraints(self.connection,
                                          tls.allowed_cn,
                                          tls.allowed_hostname):
                    return True
                # drain the body so a keep-alive connection stays in
                # sync after the rejection (empty read = client gone)
                n = int(self.headers.get("Content-Length", "0") or 0)
                while n > 0:
                    chunk = self.rfile.read(min(n, 1 << 16))
                    if not chunk:
                        break
                    n -= len(chunk)
                self._send(403, {"error": "client certificate "
                                 "constraint not satisfied"})
                return False

            def _cert_cn(self) -> str | None:
                """Verified client-cert CN, only when the listener
                actually verifies client certs."""
                if tls is None or not tls.client_cert_auth:
                    return None
                from etcd_tpu.transport import peer_common_name

                return peer_common_name(self.connection)

            def _send(self, code: int, obj: dict,
                      headers: dict | None = None) -> None:
                blob = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(blob)))
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(blob)

            # ---- v2 REST family (api/v2http client.go handler mux)
            def _v2_form(self) -> dict:
                from urllib.parse import parse_qsl, urlsplit

                form = dict(parse_qsl(urlsplit(self.path).query,
                                      keep_blank_values=True))
                n = int(self.headers.get("Content-Length", "0") or 0)
                if n:
                    body = self.rfile.read(n).decode()
                    ctype = self.headers.get("Content-Type", "")
                    if "json" in ctype:
                        try:
                            form.update(json.loads(body or "{}"))
                        except json.JSONDecodeError:
                            pass
                    else:
                        form.update(parse_qsl(body,
                                              keep_blank_values=True))
                auth = self.headers.get("Authorization", "")
                if auth.startswith("Basic "):
                    import base64 as _b64

                    try:
                        form["_basic_auth"] = _b64.b64decode(
                            auth[6:]).decode()
                    except Exception:
                        pass
                return form

            def _maybe_v2(self) -> bool:
                from urllib.parse import urlsplit

                path = urlsplit(self.path).path
                if path.startswith(KEYS_PREFIX):
                    key = path[len(KEYS_PREFIX):] or "/"
                    with api.lock:
                        st, body, hdr = v2api.keys(
                            self.command, key, self._v2_form())
                    self._send(st, body, hdr)
                    return True
                if path.startswith("/v2/watch_poll/"):
                    wid = int(path.rsplit("/", 1)[1])
                    with api.lock:
                        if self.command == "DELETE":
                            v2api.watch_cancel(wid)
                            st, body, hdr = 204, {}, {}
                        else:
                            st, body, hdr = v2api.watch_poll(wid)
                    self._send(st, body, hdr)
                    return True
                if path.startswith("/v2/members"):
                    suffix = path[len("/v2/members"):]
                    with api.lock:
                        st, body, hdr = v2api.members(
                            self.command, suffix, self._v2_form())
                    self._send(st, body, hdr)
                    return True
                if path.startswith("/v2/stats/"):
                    with api.lock:
                        st, body, hdr = v2api.stats(path.rsplit("/", 1)[1])
                    self._send(st, body, hdr)
                    return True
                if path.startswith("/v2/auth/"):
                    with api.lock:
                        st, body, hdr = v2api.auth_admin(
                            self.command, path[len("/v2/auth"):],
                            self._v2_form())
                    self._send(st, body, hdr)
                    return True
                return False

            def do_PUT(self):
                if not self._tls_gate():
                    return
                if not self._maybe_v2():
                    self._send(404, {"error": "not found"})

            def do_DELETE(self):
                if not self._tls_gate():
                    return
                if not self._maybe_v2():
                    self._send(404, {"error": "not found"})

            def do_GET(self):
                if not self._tls_gate():
                    return
                if self._maybe_v2():
                    return
                # etcdhttp: /health, /version, /metrics (api/etcdhttp)
                if self.path == "/health":
                    with api.lock:
                        try:
                            api.ec.ensure_leader()
                            self._send(200, {"health": "true"})
                        except Exception as e:
                            self._send(503, {"health": "false",
                                             "reason": str(e)})
                elif self.path == "/version":
                    with api.lock:
                        cv = api.ec.cluster_version()
                    self._send(200, {
                        "etcdserver": __version__,
                        "etcdcluster": cv or MIN_CLUSTER_VERSION,
                    })
                elif self.path == "/metrics":
                    # Prometheus exposition format (api/etcdhttp metrics):
                    # etcd-reference metric names with # HELP/# TYPE
                    # declarations and histogram _bucket/_sum/_count
                    # triplets — parseable by any exposition-format
                    # scraper (round-trip test in tests/test_v3rpc.py)
                    from etcd_tpu.models.metrics import fleet_summary
                    from etcd_tpu.models.telemetry import (
                        PROMETHEUS_CONTENT_TYPE,
                        prometheus_render,
                        server_metric_families,
                        telemetry_report,
                    )

                    with api.lock:
                        s = fleet_summary(api.ec.cl.s)
                        tele = getattr(api.ec.cl, "tele", None)
                        trep = None
                        if tele is not None:
                            try:
                                trep = telemetry_report(
                                    tele, groups=api.ec.cl.C)
                            except OverflowError:
                                # a wrapped i32 window on a long-lived
                                # server must not poison every future
                                # scrape: open a fresh window and serve
                                # this scrape without the latency
                                # families
                                api.ec.cl.reset_telemetry()
                        td = getattr(api.ec, "contention", None)
                        slow = {
                            "slow_apply_total": getattr(
                                api.ec, "slow_apply_total", 0),
                            "slow_read_indexes_total": getattr(
                                api.ec, "slow_read_index_total", 0),
                        }
                    blob = prometheus_render(server_metric_families(
                        s, trep, contention=td, slow=slow)).encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     PROMETHEUS_CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(blob)))
                    self.end_headers()
                    self.wfile.write(blob)
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):
                if not self._tls_gate():
                    return
                if self._maybe_v2():
                    return
                n = int(self.headers.get("Content-Length", "0"))
                try:
                    q = json.loads(self.rfile.read(n) or b"{}")
                except json.JSONDecodeError:
                    self._send(400, {"error": "bad json", "code": 3})
                    return
                if not isinstance(q, dict):
                    self._send(400, {"error": "request body must be a "
                                     "JSON object", "code": 3})
                    return
                # _token is a transport-layer field: a JSON body that
                # smuggles one (e.g. "cert:root") must never reach the
                # handlers as an identity
                q.pop("_token", None)
                tok = self.headers.get("Authorization")
                # "cert:" is the transport-injected identity namespace —
                # never accepted from the wire (a client must not spoof
                # a cert identity through the Authorization header)
                if tok and not tok.startswith("cert:"):
                    q["_token"] = tok
                else:
                    cn = self._cert_cn()
                    if cn is not None:
                        # AuthInfoFromTLS (store.go:985): the verified
                        # client cert CN authenticates as that user,
                        # no password/token required
                        q["_token"] = "cert:" + cn
                path = self.path
                if path.startswith("/v3/auth/"):
                    suffix = path[len("/v3/auth/"):].replace("/", "_")
                    with api.lock:
                        try:
                            self._send(200, api.auth(suffix, q))
                        except Exception as e:
                            # AuthError subclasses often carry no
                            # message — the class name IS the error
                            self._send(400, {
                                "error": str(e) or type(e).__name__,
                                "code": 3})
                    return
                name = ROUTES.get(path)
                if name is None:
                    self._send(404, {"error": f"unknown path {path}"})
                    return
                with api.lock:
                    try:
                        self._send(200, getattr(api, name)(q))
                    except ServerError as e:
                        self._send(400, {"error": str(e), "code": 3})
                    except Exception as e:  # pragma: no cover
                        self._send(500, {"error": f"{type(e).__name__}: {e}"})

        # build the SSL context BEFORE binding so a bad cert path or
        # invalid constraint combination fails without leaking a bound
        # listener socket
        ssl_ctx = tls.server_context() if tls is not None else None
        self.httpd = _QuietServer((host, port), Handler)
        if tls is not None:
            # wrap the listening socket with DEFERRED handshakes:
            # accept() stays instant in the serve_forever thread, and
            # Handler.setup() handshakes in the per-connection thread
            # (a stalled or garbage client costs one worker thread for
            # HANDSHAKE_TIMEOUT, not the accept loop). Failed
            # handshakes raise there; _QuietServer drops them silently
            # — the client sees the TLS alert, the server keeps serving.
            self.httpd.socket = ssl_ctx.wrap_socket(
                self.httpd.socket, server_side=True,
                do_handshake_on_connect=False)
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> "V3Server":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
