"""etcdutl analog: offline admin over a data directory.

The reference's etcdutl operates directly on files with no server
running (etcdutl/etcdutl: snapshot status/restore, defrag, hashkv).
Commands here work on the backend files etcd_tpu writes
(<data-dir>/member<N>.db) and the snapshot blobs etcdctl saves.

Usage:
    python -m etcd_tpu.etcdutl snapshot status snap.json
    python -m etcd_tpu.etcdutl hashkv --data-dir D --member 0
    python -m etcd_tpu.etcdutl defrag --data-dir D
    python -m etcd_tpu.etcdutl status --data-dir D
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def _member_paths(data_dir: str) -> list[str]:
    return sorted(glob.glob(os.path.join(data_dir, "member*.db")))


def _load(path: str):
    from etcd_tpu.storage import schema
    from etcd_tpu.storage.backend import Backend

    be = Backend(path)
    meta = schema.load_applied_meta(be) or {}
    store = schema.load_mvcc(
        be,
        max_rev=meta.get("current_rev"),
        compact_rev=meta.get("compact_rev", 0),
    )
    return be, meta, store


def cmd_snapshot_status(args) -> int:
    with open(args.path, "rb") as f:
        snap = json.load(f)
    kv = snap.get("kv", {})
    print(json.dumps({
        "applied_index": snap.get("applied_index"),
        "revision": kv.get("current_rev"),
        "compact_revision": kv.get("compact_rev"),
        "total_key_revisions": len(kv.get("revs", [])),
        "alarms": snap.get("alarms", []),
    }))
    return 0


def cmd_hashkv(args) -> int:
    path = os.path.join(args.data_dir, f"member{args.member}.db")
    _, meta, store = _load(path)
    print(json.dumps({
        "member": args.member,
        "hash": store.hash_kv(),
        "revision": store.current_rev,
        "consistent_index": meta.get("consistent_index", 0),
    }))
    return 0


def cmd_defrag(args) -> int:
    for path in _member_paths(args.data_dir):
        from etcd_tpu.storage.backend import Backend

        be = Backend(path)
        before = be.size()
        be.defrag()
        be.close()
        print(f"{os.path.basename(path)}: {before} -> {be.size()} bytes")
    return 0


def cmd_status(args) -> int:
    out = []
    for path in _member_paths(args.data_dir):
        be, meta, store = _load(path)
        out.append({
            "member": os.path.basename(path),
            "size": be.size(),
            "size_in_use": be.size_in_use(),
            "consistent_index": meta.get("consistent_index", 0),
            "term": meta.get("term", 0),
            "revision": store.current_rev,
            "compact_revision": store.compact_rev,
            "keys": len(store.index),
        })
    print(json.dumps(out, indent=2))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="etcdutl-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    sn = sub.add_parser("snapshot")
    ssub = sn.add_subparsers(dest="snap_cmd", required=True)
    st = ssub.add_parser("status")
    st.add_argument("path")

    h = sub.add_parser("hashkv")
    h.add_argument("--data-dir", required=True)
    h.add_argument("--member", type=int, default=0)

    d = sub.add_parser("defrag")
    d.add_argument("--data-dir", required=True)

    s = sub.add_parser("status")
    s.add_argument("--data-dir", required=True)

    args = p.parse_args(argv)
    if args.cmd == "snapshot":
        return cmd_snapshot_status(args)
    if args.cmd == "hashkv":
        return cmd_hashkv(args)
    if args.cmd == "defrag":
        return cmd_defrag(args)
    return cmd_status(args)


if __name__ == "__main__":
    sys.exit(main())
