"""Sharding the fleet over a device mesh.

The reference scales by running more processes connected over rafthttp
(server/etcdserver/api/rafthttp/) — its NCCL/MPI analog. The TPU-native
equivalent shards the *clusters* axis of the ``[C, M]`` fleet over a
``jax.sharding.Mesh``: every cluster's message exchange is a within-cluster
transpose (member axis stays on-device), so the clusters axis is purely
data-parallel and XLA places one shard per device with zero collectives in
the steady state — the ICI/DCN budget is spent only by the host driver
(proposal feed / applied drain), mirroring rafthttp's "client traffic at the
edge, peer traffic inside" split.

Two entry points:
  * :func:`build_sharded_round` — jit of the fused round with
    ``NamedSharding`` constraints on the clusters axis (lets XLA do the
    placement; the program is identical to the single-device one).
  * :func:`build_shard_map_round` — explicit ``shard_map`` over the clusters
    axis, the form that composes with cross-shard collectives (e.g. global
    invariant checks via ``psum``) and with a second DCN mesh axis.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from etcd_tpu.models.engine import build_round
from etcd_tpu.types import Spec
from etcd_tpu.utils.config import RaftConfig

CLUSTER_AXIS = "clusters"


def make_fleet_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the clusters axis. On multi-host topologies the same
    axis spans DCN transparently (device order follows jax.devices())."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    import numpy as np

    return Mesh(np.asarray(devices), (CLUSTER_AXIS,))


def _c_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(CLUSTER_AXIS))


def shard_fleet(mesh: Mesh, *trees):
    """Place every leaf of each pytree with its leading C axis split over the
    mesh. Returns the trees device-put with NamedSharding."""
    sh = _c_sharding(mesh)

    def put(x):
        return jax.device_put(x, sh)

    out = tuple(jax.tree.map(put, t) for t in trees)
    return out[0] if len(out) == 1 else out


def build_sharded_round(cfg: RaftConfig, spec: Spec, mesh: Mesh):
    """Jitted round with all inputs/outputs constrained to the clusters
    sharding. Identical math to engine.build_round; placement only."""
    round_fn = build_round(cfg, spec)
    sh = _c_sharding(mesh)

    def constrained(*args):
        args = tuple(
            jax.tree.map(lambda x: jax.lax.with_sharding_constraint(x, sh), a)
            for a in args
        )
        state, inbox = round_fn(*args)
        state = jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, sh), state
        )
        inbox = jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, sh), inbox
        )
        return state, inbox

    return jax.jit(constrained)


def build_shard_map_round(cfg: RaftConfig, spec: Spec, mesh: Mesh):
    """shard_map form: each device steps its C/n_devices cluster shard
    locally. Composes with cross-shard collectives (psum of invariant
    violations etc.) and nested member-axis sharding later."""
    round_fn = build_round(cfg, spec)
    pspec = P(CLUSTER_AXIS)
    n_args = 9  # state, inbox, prop_len, prop_data, prop_type, ri_ctx, hup, tick, keep

    fn = shard_map(
        round_fn,
        mesh=mesh,
        in_specs=(pspec,) * n_args,
        out_specs=(pspec, pspec),
        check_rep=False,
    )
    return jax.jit(fn)


def build_scan_rounds(cfg: RaftConfig, spec: Spec, mesh: Mesh | None, rounds: int,
                      use_shard_map: bool = False):
    """Fixed-schedule driver: scan `rounds` lockstep rounds entirely on
    device with a constant per-round input (the benchmark hot loop — no
    host round-trips, mirroring the reference's node.run select loop staying
    in one goroutine).

    Returns jitted fn(state, inbox, prop_len, prop_data, prop_type, ri_ctx,
    do_hup, do_tick, keep_mask) -> (state, inbox).
    """
    round_fn = build_round(cfg, spec)

    def many(state, inbox, prop_len, prop_data, prop_type, ri_ctx, do_hup,
             do_tick, keep_mask):
        def body(carry, _):
            st, ib = carry
            st, ib = round_fn(
                st, ib, prop_len, prop_data, prop_type, ri_ctx, do_hup,
                do_tick, keep_mask,
            )
            return (st, ib), ()

        (state, inbox), _ = jax.lax.scan(
            body, (state, inbox), None, length=rounds
        )
        return state, inbox

    if mesh is None:
        return jax.jit(many)
    if use_shard_map:
        pspec = P(CLUSTER_AXIS)
        fn = shard_map(
            many,
            mesh=mesh,
            in_specs=(pspec,) * 9,
            out_specs=(pspec, pspec),
            check_rep=False,
        )
        return jax.jit(fn)
    sh = _c_sharding(mesh)

    def constrained(*args):
        args = tuple(
            jax.tree.map(lambda x: jax.lax.with_sharding_constraint(x, sh), a)
            for a in args
        )
        return many(*args)

    return jax.jit(constrained)
