"""Batched engine: vmapped node rounds + message exchange.

The reference runs one goroutine per node and moves messages through
rafthttp streams (server/etcdserver/api/rafthttp/). Here a fleet of
``C x M`` nodes steps in lockstep: ``jax.vmap`` over members then clusters
turns the per-node round into one fused XLA program, and the "network" is a
transpose of the dense outbox tensor ``[from, to, K, C] -> [to, from, K, C]``
with a multiplicative keep-mask standing in for drop/partition faults
(rafttest/network.go:33-64's drop/disconnect semantics; dropping is legal
per the transport contract, etcdserver/raft.go:107-110).

Fleet layout: **clusters-minor** — every leaf is ``[M, feature..., C]``
with the huge batch axis LAST. TPU tiles the two minor dims to (8, 128)
sublanes x lanes; with clusters leading, a ``[C, 5, 5]`` leaf pads 41x and
the fleet OOMs at scale, while clusters-minor pads only the tiny member
axis (<=1.6x). The member axes stay leading and fully on-device, which is
where the per-round message transpose happens.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from etcd_tpu.models.raft import node_round
from etcd_tpu.models.state import NodeState, init_node
from etcd_tpu.ops.outbox import Outbox
from etcd_tpu.types import Msg, Spec
from etcd_tpu.utils.config import RaftConfig


def empty_inbox(spec: Spec, C: int) -> Msg:
    """Zeroed inbox [to, from, K, (E,) C]."""
    from etcd_tpu.types import empty_msg

    return jax.tree.map(
        lambda x: jnp.broadcast_to(
            x[..., None], (spec.M, spec.M, spec.K) + x.shape + (C,)
        ),
        empty_msg(spec),
    )


def init_fleet(
    spec: Spec,
    C: int,
    voters: jnp.ndarray | None = None,
    learners: jnp.ndarray | None = None,
    seed: int = 0,
    election_tick: int = 10,
) -> NodeState:
    """State pytree with leading [C, M] axes. `voters`/`learners` may be
    [M] (shared) or [C, M] masks."""
    if voters is None:
        voters = jnp.ones((spec.M,), jnp.bool_)
    if voters.ndim == 1:
        voters = jnp.broadcast_to(voters, (C, spec.M))
    if learners is None:
        learners = jnp.zeros((C, spec.M), jnp.bool_)
    elif learners.ndim == 1:
        learners = jnp.broadcast_to(learners, (C, spec.M))

    def one(c, m):
        return init_node(
            spec, m, voters[c], learners[c], seed=c * 1_000_003 + seed,
            election_tick=election_tick,
        )

    # members leading (axis 0), clusters minor (axis -1)
    return jax.vmap(
        lambda m: jax.vmap(lambda c: one(c, m), out_axes=-1)(
            jnp.arange(C, dtype=jnp.int32)
        )
    )(jnp.arange(spec.M, dtype=jnp.int32))


def build_round(cfg: RaftConfig, spec: Spec, with_drop_count: bool = False):
    """Returns round_fn(state, inbox, prop_len, prop_data, prop_type,
    ri_ctx, do_hup, do_tick, keep_mask) -> (state, next_inbox).

    Shapes (clusters-minor): state/* leaves [M, ..., C]; inbox leaves
    [M(to), M(from), K, (E,) C]; prop_len/ri_ctx/do_hup/do_tick [M, C];
    prop_data/prop_type [M, E, C]; keep_mask [M(from), M(to), C] bool
    (True = deliver).

    with_drop_count: also return the number of emitted messages the
    keep-mask killed this round (for the metrics pipeline).
    """
    node_fn = functools.partial(node_round, cfg, spec)
    # outer vmap: member axis (leading); inner vmap: cluster axis (minor)
    vmapped = jax.vmap(jax.vmap(node_fn, in_axes=-1, out_axes=-1))

    def round_fn(
        state: NodeState,
        inbox: Msg,
        prop_len,
        prop_data,
        prop_type,
        ri_ctx,
        do_hup,
        do_tick,
        keep_mask,
    ):
        state, ob = vmapped(
            state, inbox, prop_len, prop_data, prop_type, ri_ctx, do_hup, do_tick
        )
        msgs = ob.msgs  # leaves [from, to, K, (E,) C]
        # self-loops (MsgHup-to-self etc.) are local, never subject to faults
        keep = keep_mask | jnp.eye(spec.M, dtype=jnp.bool_)[:, :, None]
        emitted = (msgs.type != 0).sum() if with_drop_count else None
        msgs = msgs.replace(type=jnp.where(keep[:, :, None, :], msgs.type, 0))
        next_inbox = jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), msgs)
        if with_drop_count:
            dropped = emitted - (next_inbox.type != 0).sum()
            return state, next_inbox, dropped
        return state, next_inbox

    return round_fn


class RaftEngine:
    """Jitted lockstep driver for a fleet of C x M-member Raft groups."""

    def __init__(
        self,
        spec: Spec = Spec(),
        cfg: RaftConfig = RaftConfig(),
        C: int = 1,
        voters=None,
        learners=None,
        seed: int = 0,
    ):
        self.spec, self.cfg, self.C = spec, cfg, C
        self.state = init_fleet(
            spec, C, voters, learners, seed, election_tick=cfg.election_tick
        )
        self.inbox = empty_inbox(spec, C)
        self.keep_mask = jnp.ones((spec.M, spec.M, C), jnp.bool_)
        self._round = jax.jit(build_round(cfg, spec))

    # -- one lockstep round -------------------------------------------------
    def step(
        self,
        prop_len=None,
        prop_data=None,
        prop_type=None,
        ri_ctx=None,
        do_hup=None,
        do_tick=False,
    ):
        """All inputs use the device (clusters-minor) layout:
        prop_len/ri_ctx/do_hup/do_tick [M, C]; prop_data/prop_type
        [M, E, C]."""
        C, M, E = self.C, self.spec.M, self.spec.E
        z2 = jnp.zeros((M, C), jnp.int32)
        prop_len = z2 if prop_len is None else jnp.asarray(prop_len, jnp.int32)
        prop_data = (
            jnp.zeros((M, E, C), jnp.int32)
            if prop_data is None
            else jnp.asarray(prop_data, jnp.int32)
        )
        prop_type = (
            jnp.zeros((M, E, C), jnp.int32)
            if prop_type is None
            else jnp.asarray(prop_type, jnp.int32)
        )
        ri_ctx = z2 if ri_ctx is None else jnp.asarray(ri_ctx, jnp.int32)
        do_hup = (
            jnp.zeros((M, C), jnp.bool_)
            if do_hup is None
            else jnp.asarray(do_hup, jnp.bool_)
        )
        if isinstance(do_tick, bool):
            do_tick = jnp.full((M, C), do_tick, jnp.bool_)
        else:
            do_tick = jnp.asarray(do_tick, jnp.bool_)
        self.state, self.inbox = self._round(
            self.state,
            self.inbox,
            prop_len,
            prop_data,
            prop_type,
            ri_ctx,
            do_hup,
            do_tick,
            self.keep_mask,
        )
        return self.state

    def pending_messages(self) -> int:
        return int((self.inbox.type != 0).sum())
