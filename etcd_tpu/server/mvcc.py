"""Multi-version KV store — the host-side applied state machine.

Mirrors the reference's ``server/storage/mvcc`` semantics with an idiomatic
Python layout (the device engine replicates *entry references*; each member
applies them to one of these stores, like each etcd node applies to its own
bbolt):

  * every write gets a ``revision{main, sub}`` (mvcc/revision.go): main
    increments once per applied txn, sub per op within it.
  * ``treeIndex`` (mvcc/index.go:25-52) maps key -> keyIndex; here a dict of
    key -> KeyIndex plus a lazily-sorted key list for range scans (bisect
    stands in for the google/btree of degree 32).
  * ``KeyIndex`` (mvcc/key_index.go:70-74) keeps *generations* separated by
    tombstones so historical reads at any revision resolve correctly.
  * reads at a revision walk the index, then fetch values from the revision-
    keyed store (the bbolt "key" bucket analog, schema/bucket.go:97).
  * compaction (mvcc/kvstore_compaction.go) drops versions <= compact_rev
    except each key's latest, and whole keys whose latest is a tombstone.

Sizes are tracked so the quota/alarm path (NOSPACE) has something to check.
"""
from __future__ import annotations

import bisect
import dataclasses


class MVCCError(Exception):
    pass


class ErrCompacted(MVCCError):
    """mvcc.ErrCompacted: requested rev <= compacted revision."""


class ErrFutureRev(MVCCError):
    """mvcc.ErrFutureRev: requested rev > current revision."""


@dataclasses.dataclass(frozen=True, order=True)
class Revision:
    main: int
    sub: int = 0


@dataclasses.dataclass
class KeyValue:
    """mvccpb.KeyValue (api/mvccpb/kv.proto)."""

    key: bytes
    value: bytes
    create_revision: int
    mod_revision: int
    version: int
    lease: int = 0


class KeyIndex:
    """key_index.go: per-key revision history in generations."""

    __slots__ = ("key", "generations")

    def __init__(self, key: bytes):
        self.key = key
        self.generations: list[list[Revision]] = []

    def put(self, rev: Revision) -> None:
        if not self.generations:
            self.generations.append([])
        self.generations[-1].append(rev)

    def tombstone(self, rev: Revision) -> None:
        self.put(rev)
        self.generations.append([])  # open a fresh (empty) generation

    def _walk(self, at_rev: int):
        """(gi, revs_visible) for the generation live at at_rev, where
        revs_visible are its revisions with main <= at_rev (key_index.go
        findGeneration + walk)."""
        for gi in range(len(self.generations) - 1, -1, -1):
            gen = self.generations[gi]
            if not gen or gen[0].main > at_rev:
                continue
            vis = [r for r in gen if r.main <= at_rev]
            if not vis:
                return None
            # closed generation whose visible tail is its tombstone => dead
            closed = gi < len(self.generations) - 1
            if closed and vis[-1] == gen[-1]:
                return None
            return gi, vis
        return None

    def get(self, at_rev: int) -> Revision | None:
        """Latest live revision <= at_rev, or None if absent/tombstoned."""
        hit = self._walk(at_rev)
        return hit[1][-1] if hit else None

    def created_version(self, at_rev: int) -> tuple[Revision, int] | None:
        """(create_revision, version) for the generation live at at_rev."""
        hit = self._walk(at_rev)
        if not hit:
            return None
        gi, vis = hit
        return self.generations[gi][0], len(vis)

    def compact(self, at_rev: int) -> bool:
        """Drop revisions <= at_rev except the live one; returns True when
        the whole keyIndex is empty and should be removed."""
        new_gens: list[list[Revision]] = []
        for gi, gen in enumerate(self.generations):
            if not gen:
                new_gens.append(gen)
                continue
            closed = gi < len(self.generations) - 1
            if closed and gen[-1].main <= at_rev:
                continue  # whole generation (incl. tombstone) compacted away
            keep = [r for r in gen if r.main > at_rev]
            live = [r for r in gen if r.main <= at_rev]
            if live and not (closed and live[-1] == gen[-1]):
                keep = [live[-1]] + keep
            new_gens.append(keep)
        # drop leading empties
        while len(new_gens) > 1 and not new_gens[0]:
            new_gens.pop(0)
        self.generations = new_gens
        return all(not g for g in self.generations)


class MVCCStore:
    """mvcc.store (kvstore.go:59-87) + treeIndex, single-writer."""

    def __init__(self):
        self.index: dict[bytes, KeyIndex] = {}
        self._sorted_keys: list[bytes] = []
        self._sorted_dirty = False
        # revision-keyed value store: (main, sub) -> KeyValue (+ tombstone flag)
        self.revs: dict[tuple[int, int], tuple[KeyValue, bool]] = {}
        self.current_rev = 1  # reference boots at rev 1 (kvstore.go:91-113)
        self.compact_rev = 0
        self.size = 0

    # -- internals ----------------------------------------------------------
    def _keys(self) -> list[bytes]:
        if self._sorted_dirty:
            self._sorted_keys = sorted(self.index.keys())
            self._sorted_dirty = False
        return self._sorted_keys

    def _range_keys(self, key: bytes, range_end: bytes | None) -> list[bytes]:
        """etcd range semantics: range_end None => single key; b'\\0' =>
        from key to end; else half-open [key, range_end)."""
        if range_end is None:
            return [key] if key in self.index else []
        ks = self._keys()
        lo = bisect.bisect_left(ks, key)
        if range_end == b"\x00":
            return ks[lo:]
        hi = bisect.bisect_left(ks, range_end)
        return ks[lo:hi]

    def _check_rev(self, rev: int) -> int:
        if rev <= 0 or rev > self.current_rev:
            if rev > self.current_rev:
                raise ErrFutureRev(rev)
            return self.current_rev
        if rev < self.compact_rev:
            raise ErrCompacted(rev)
        return rev

    # -- txn API (kvstore_txn.go) -------------------------------------------
    def write_txn(self) -> "WriteTxn":
        return WriteTxn(self)

    def range(
        self,
        key: bytes,
        range_end: bytes | None = None,
        rev: int = 0,
        limit: int = 0,
        count_only: bool = False,
    ) -> tuple[list[KeyValue], int, int]:
        """(kvs, count, rev_used). rev=0 means current."""
        at = self._check_rev(rev if rev > 0 else self.current_rev)
        return self._range_at(at, key, range_end, limit, count_only)

    def _range_at(
        self,
        at: int,
        key: bytes,
        range_end: bytes | None = None,
        limit: int = 0,
        count_only: bool = False,
    ) -> tuple[list[KeyValue], int, int]:
        kvs: list[KeyValue] = []
        count = 0
        for k in self._range_keys(key, range_end):
            ki = self.index.get(k)
            if ki is None:
                continue
            r = ki.get(at)
            if r is None:
                continue
            count += 1
            if count_only:
                continue
            if limit and len(kvs) >= limit:
                continue
            kv, tomb = self.revs[(r.main, r.sub)]
            if not tomb:
                kvs.append(kv)
        return kvs, count, at

    def compact(self, rev: int) -> None:
        if rev <= self.compact_rev:
            raise ErrCompacted(rev)
        if rev > self.current_rev:
            raise ErrFutureRev(rev)
        self.compact_rev = rev
        dead_keys = []
        for k, ki in self.index.items():
            if ki.compact(rev):
                dead_keys.append(k)
        for k in dead_keys:
            del self.index[k]
        self._sorted_dirty = True
        keep = set()
        for ki in self.index.values():
            for gen in ki.generations:
                for r in gen:
                    keep.add((r.main, r.sub))
        for rk in [rk for rk in self.revs if rk[0] <= rev and rk not in keep]:
            kv, _ = self.revs.pop(rk)
            self.size -= len(kv.key) + len(kv.value)

    def hash_kv(self, rev: int = 0) -> int:
        """Maintenance/HashKV analog (mvcc/hash.go): order-independent-free
        digest of live revision data up to rev."""
        import zlib

        at = rev if rev > 0 else self.current_rev
        h = 0
        for (main, sub), (kv, tomb) in sorted(self.revs.items()):
            if main > at:
                continue
            rec = b"%d/%d/%s/%s/%d" % (main, sub, kv.key, kv.value, tomb)
            h = zlib.crc32(rec, h)
        return h

    # -- snapshot (Maintenance.Snapshot / etcdutl analog) --------------------
    def to_snapshot(self) -> dict:
        return {
            "current_rev": self.current_rev,
            "compact_rev": self.compact_rev,
            "revs": [
                (m, s, kv.key, kv.value, kv.create_revision, kv.mod_revision,
                 kv.version, kv.lease, tomb)
                for (m, s), (kv, tomb) in sorted(self.revs.items())
            ],
            "index": [
                (k, [[(r.main, r.sub) for r in gen] for gen in ki.generations])
                for k, ki in sorted(self.index.items())
            ],
        }

    @classmethod
    def from_snapshot(cls, snap: dict) -> "MVCCStore":
        st = cls()
        st.current_rev = snap["current_rev"]
        st.compact_rev = snap["compact_rev"]
        for m, s, k, v, cr, mr, ver, lease, tomb in snap["revs"]:
            st.revs[(m, s)] = (KeyValue(k, v, cr, mr, ver, lease), tomb)
            st.size += len(k) + len(v)
        for k, gens in snap["index"]:
            ki = KeyIndex(k)
            ki.generations = [[Revision(m, s) for m, s in gen] for gen in gens]
            st.index[k] = ki
        st._sorted_dirty = True
        return st


class WriteTxn:
    """One applied entry's write transaction: all ops share revision main =
    current_rev + 1, distinct subs (kvstore_txn.go:127-240); End() bumps
    current_rev and reports events for the watch layer
    (watchable_store_txn.go:22)."""

    def __init__(self, store: MVCCStore):
        self.s = store
        self.main = store.current_rev + 1
        self.sub = 0
        self.events: list[tuple[str, KeyValue, KeyValue | None]] = []
        self._wrote = False

    def range(self, key: bytes, range_end: bytes | None = None,
              limit: int = 0, count_only: bool = False):
        """Read *inside* the txn: sees this txn's own earlier writes
        (kvstore_txn.go's read buffer over the uncommitted batch)."""
        return self.s._range_at(self.main, key, range_end, limit, count_only)

    def put(self, key: bytes, value: bytes, lease: int = 0) -> int:
        s = self.s
        rev = Revision(self.main, self.sub)
        ki = s.index.get(key)
        if ki is None:
            ki = KeyIndex(key)
            s.index[key] = ki
            s._sorted_dirty = True
        # visibility at self.main: ops in this txn see earlier ops of the
        # same txn (intra-txn read-your-writes, kvstore_txn.go tx buffer)
        prev = ki.created_version(self.main)
        if prev is None:
            create, version = rev, 1
        else:
            create, version = prev[0], prev[1] + 1
        prev_kv = None
        pr = ki.get(self.main)
        if pr is not None:
            prev_kv = s.revs[(pr.main, pr.sub)][0]
        ki.put(rev)
        kv = KeyValue(key, value, create.main, rev.main, version, lease)
        s.revs[(rev.main, rev.sub)] = (kv, False)
        s.size += len(key) + len(value)
        self.events.append(("put", kv, prev_kv))
        self.sub += 1
        self._wrote = True
        return rev.main

    def delete_range(self, key: bytes, range_end: bytes | None = None) -> int:
        s = self.s
        deleted = 0
        for k in list(s._range_keys(key, range_end)):
            ki = s.index.get(k)
            if ki is None:
                continue
            live = ki.get(self.main)  # sees this txn's own writes
            if live is None:
                continue
            rev = Revision(self.main, self.sub)
            prev_kv = s.revs[(live.main, live.sub)][0]
            ki.tombstone(rev)
            kv = KeyValue(k, b"", 0, rev.main, 0)
            s.revs[(rev.main, rev.sub)] = (kv, True)
            self.events.append(("delete", kv, prev_kv))
            self.sub += 1
            deleted += 1
            self._wrote = True
        return deleted

    def end(self) -> int:
        if self._wrote:
            self.s.current_rev = self.main
        return self.s.current_rev
